"""Streaming (chunk-accumulated) objective + host-driven L-BFGS/OWL-QN.

Reference counterpart: the per-iteration Spark round —
``broadcast(w) → per-partition aggregator fold → treeAggregate`` —
whose partitions never co-reside in memory (SURVEY.md §2.2, §5.8
[expected structure, mount unavailable]).  Here the "partitions" are
the congruent device-program chunks of ``data.chunked_batch``: each
objective evaluation replays ONE compiled per-chunk program K times,
double-buffering the host→device transfer of chunk i+1 under chunk i's
compute, and accumulates (value, gradient, HVP, Hessian-diagonal)
partials on device.  Exact: every data-side quantity is a linear
reduction over examples; regularization and the Gaussian prior are
example-independent and added once, outside the chunk loop.

The resident solvers (``optim.lbfgs`` / ``optim.tron``) run their whole
optimize loop as one device program — impossible when each objective
evaluation needs host-side chunk swaps.  ``streaming_lbfgs_solve`` is
the host-driven mirror of ``lbfgs_solve``: the same two-loop recursion,
Armijo backtracking (with the OWL-QN orthant projection and
pseudo-gradient), curvature-guarded (s, y) updates, and convergence
tests, but with a Python outer loop calling a host-level
``value_and_grad``.  Per-iteration [dim]-vector math dispatches eagerly
(a handful of cached device ops — microseconds of compute); the data
passes dominate, exactly as in the reference's driver loop.
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.chunked_batch import ChunkedBatch
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.optim.base import (
    OptimizationResult,
    OptimizerConfig,
    StatesTracker,
    grad_converged,
    loss_converged,
)
from photon_ml_tpu.optim.lbfgs import _pseudo_gradient

logger = logging.getLogger(__name__)

Array = jax.Array

_CURVATURE_EPS = 1e-10


def _place_chunk(chunk, mesh):
    """Host chunk → device: plain device_put, or example-sharded
    assembly of the per-device sub-batches onto the mesh."""
    if mesh is None:
        return jax.device_put(chunk)
    from jax.sharding import NamedSharding

    from photon_ml_tpu.parallel.mesh import batch_spec

    devices = list(mesh.devices.flat)
    sharding = NamedSharding(mesh, batch_spec())

    def asm(*leaves):
        placed = [jax.device_put(lf, d) for lf, d in zip(leaves, devices)]
        gshape = ((len(devices) * leaves[0].shape[0],)
                  + tuple(leaves[0].shape[1:]))
        return jax.make_array_from_single_device_arrays(
            gshape, sharding, placed)

    return jax.tree.map(asm, *chunk)


class ChunkedGLMObjective:
    """``GLMObjective`` surface over a ``ChunkedBatch``.

    Methods take only ``w`` (the batch is owned): the streaming solver
    cannot donate or close over a resident batch, so the usual
    ``(w, batch)`` calling convention has no meaning here.

    ``max_resident`` chunks stay live on device across evaluations
    (datasets that fit entirely set it ≥ n_chunks and pay the transfer
    once — the resident and streaming regimes are one code path);
    beyond it, chunks are re-placed each pass, double-buffered.
    """

    def __init__(self, objective: GLMObjective, batch: ChunkedBatch,
                 max_resident: int = 1):
        self.objective = objective
        self.batch = batch
        self.max_resident = max_resident
        self._cache: dict = {}
        inner = objective.replace(
            reg=RegularizationContext.none(), prior=None)
        self._mesh = batch.mesh
        if self._mesh is not None:
            from photon_ml_tpu.parallel import DistributedGLMObjective

            self._inner = DistributedGLMObjective(
                objective=inner, mesh=self._mesh)
        else:
            self._inner = inner
        # One jitted program per method, shared by every congruent
        # chunk.  The objective rides as a pytree ARGUMENT (not a
        # closure) so its [dim] reg/norm arrays don't bake into the
        # HLO as constants.
        self._j_vg = jax.jit(lambda o, w, b: o.value_and_gradient(w, b))
        self._j_val = jax.jit(lambda o, w, b: o.value(w, b))
        self._j_hvp = jax.jit(lambda o, w, v, b: o.hessian_vector(w, v, b))
        self._j_hd = jax.jit(lambda o, w, b: o.hessian_diagonal(w, b))
        self._j_margins = jax.jit(
            lambda o, w, b: o.predict_margins(w, b))
        if self._mesh is not None:
            self._j_xdot = jax.jit(
                lambda w, b: self._inner.x_dot(w, b))
        else:
            self._j_xdot = jax.jit(lambda w, b: b.x_dot(w))

    # -- chunk residency ---------------------------------------------------

    def invalidate(self) -> None:
        """Drop device copies (after ``ChunkedBatch.set_offsets``)."""
        self._cache.clear()

    def _get(self, i: int):
        if i in self._cache:
            return self._cache[i]
        b = _place_chunk(self.batch.chunks[i], self._mesh)
        if len(self._cache) < self.max_resident:
            self._cache[i] = b
        return b

    def _sweep(self, per_chunk, combine):
        """Stream all chunks through ``per_chunk``, double-buffered."""
        k = self.batch.n_chunks
        acc = None
        nxt = self._get(0)
        for i in range(k):
            cur = nxt
            if i + 1 < k:
                nxt = self._get(i + 1)   # async transfer under compute
            out = per_chunk(cur)
            acc = out if acc is None else combine(acc, out)
        return acc

    # -- TwiceDiffFunction surface (batch owned) ---------------------------

    def value(self, w: Array) -> Array:
        w = jnp.asarray(w, jnp.float32)
        val = self._sweep(lambda b: self._j_val(self._inner, w, b),
                          lambda a, x: a + x)
        val = val + self.objective.reg.l2_value(w)
        if self.objective.prior is not None:
            val = val + self.objective.prior.value(w)
        return val

    def value_and_gradient(self, w: Array) -> tuple[Array, Array]:
        w = jnp.asarray(w, jnp.float32)
        f, g = self._sweep(
            lambda b: self._j_vg(self._inner, w, b),
            lambda a, x: (a[0] + x[0], a[1] + x[1]))
        reg = self.objective.reg
        f = f + reg.l2_value(w)
        g = g + reg.l2_gradient(w)
        if self.objective.prior is not None:
            f = f + self.objective.prior.value(w)
            g = g + self.objective.prior.gradient(w)
        return f, g

    def gradient(self, w: Array) -> Array:
        return self.value_and_gradient(w)[1]

    def hessian_vector(self, w: Array, v: Array) -> Array:
        w = jnp.asarray(w, jnp.float32)
        v = jnp.asarray(v, jnp.float32)
        hv = self._sweep(lambda b: self._j_hvp(self._inner, w, v, b),
                         lambda a, x: a + x)
        hv = hv + self.objective.reg.l2_hessian_vector(v)
        if self.objective.prior is not None:
            hv = hv + self.objective.prior.hessian_vector(v)
        return hv

    def hessian_diagonal(self, w: Array) -> Array:
        w = jnp.asarray(w, jnp.float32)
        hd = self._sweep(lambda b: self._j_hd(self._inner, w, b),
                         lambda a, x: a + x)
        hd = hd + self.objective.reg.l2_hessian_diagonal(w)
        if self.objective.prior is not None:
            hd = hd + self.objective.prior.hessian_diagonal()
        return hd

    def _per_example(self, fn) -> np.ndarray:
        """Concatenate a per-chunk per-example quantity over all chunks
        — [n] host array (n·f32 stays bounded; only plans/features were
        too big for residency)."""
        outs = []
        k = self.batch.n_chunks
        nxt = self._get(0)
        for i in range(k):
            cur = nxt
            if i + 1 < k:
                nxt = self._get(i + 1)
            m = fn(cur)
            lo, hi = self.batch.chunk_slice(i)
            outs.append(np.asarray(m)[: hi - lo])
        return np.concatenate(outs) if outs else np.zeros(0, np.float32)

    def predict_margins(self, w: Array) -> np.ndarray:
        """Per-example margins (offsets included) over all chunks."""
        w = jnp.asarray(w, jnp.float32)
        return self._per_example(
            lambda b: self._j_margins(self._inner, w, b))

    def x_dot(self, w: Array) -> np.ndarray:
        """Raw X·w per example (offset-free scoring, the GAME
        ``CoordinateDataScores`` convention)."""
        w = jnp.asarray(w, jnp.float32)
        return self._per_example(lambda b: self._j_xdot(w, b))


def streaming_lbfgs_solve(
    value_and_grad,
    w0: Array,
    config: OptimizerConfig = OptimizerConfig(),
    l1_weight=None,
) -> OptimizationResult:
    """Host-driven L-BFGS / OWL-QN over an expensive (streamed)
    ``value_and_grad`` — the chunked mirror of ``optim.lbfgs
    .lbfgs_solve`` (same math, same convergence semantics; the outer
    loop is Python because each evaluation swaps chunks through HBM).
    """
    m = config.lbfgs_memory
    w = jnp.asarray(w0, jnp.float32)
    owlqn = l1_weight is not None
    l1 = (jnp.broadcast_to(jnp.asarray(l1_weight, w.dtype), w.shape)
          if owlqn else None)

    def full_value_grad(w_):
        f, g = value_and_grad(w_)
        if owlqn:
            f = f + jnp.sum(l1 * jnp.abs(w_))
        return f, g

    def pgrad(g_, w_):
        return _pseudo_gradient(g_, w_, l1) if owlqn else g_

    f, g = full_value_grad(w)
    pg = pgrad(g, w)
    g0_norm = float(jnp.linalg.norm(pg))
    tracker = StatesTracker.create(config.max_iters)
    if config.track_states:
        tracker = tracker.record(jnp.asarray(0, jnp.int32), f,
                                 jnp.asarray(g0_norm))

    s_hist: list = []   # newest first
    y_hist: list = []
    rho_hist: list = []
    converged = bool(grad_converged(jnp.asarray(g0_norm),
                                    jnp.asarray(g0_norm),
                                    config.tolerance))
    it = 0
    while not converged and it < config.max_iters:
        # Two-loop recursion over the (s, y) history.
        q = pg
        alphas = []
        for s, y, rho in zip(s_hist, y_hist, rho_hist):
            a = rho * jnp.vdot(s, q)
            alphas.append(a)
            q = q - a * y
        if s_hist:
            y_new = y_hist[0]
            gamma = 1.0 / jnp.maximum(
                rho_hist[0] * jnp.vdot(y_new, y_new), _CURVATURE_EPS)
        else:
            gamma = 1.0
        r = gamma * q
        for (s, y, rho), a in zip(reversed(list(zip(s_hist, y_hist,
                                                    rho_hist))),
                                  reversed(alphas)):
            beta = rho * jnp.vdot(y, r)
            r = r + s * (a - beta)
        d = -r
        if owlqn:
            d = jnp.where(d * -pg > 0.0, d, 0.0)
            xi = jnp.where(w != 0.0, jnp.sign(w), jnp.sign(-pg))
        # Steepest-descent safeguard on numerical breakdown.
        if float(jnp.vdot(pg, d)) >= 0.0:
            d = -pg

        # Backtracking Armijo (modified condition under the orthant
        # projection — identical to optim.lbfgs._line_search).
        # Backtracking mirror of optim.lbfgs._line_search: on Armijo
        # accept the trial commits; after ls_max_steps backtracks the
        # LAST trial commits anyway (the resident while_loop exits with
        # it), and in both cases only a STRICT decrease counts as
        # progress (ok = f_new < f0) — a zero-decrease step means
        # progress is below f32 measurement precision and the solve
        # stall-terminates rather than grinds.
        alpha = 1.0
        for _ in range(config.ls_max_steps + 1):
            w_try = w + alpha * d
            if owlqn:
                w_try = jnp.where(jnp.sign(w_try) == xi, w_try, 0.0)
            f_try, g_try = full_value_grad(w_try)
            if float(f_try) <= float(
                    f + config.ls_c1 * jnp.vdot(pg, w_try - w)):
                break
            alpha *= config.ls_shrink
        w_new, f_new, g_new = w_try, f_try, g_try
        ls_ok = float(f_new) < float(f)
        if ls_ok:
            s = w_new - w
            y = g_new - g
            sy = float(jnp.vdot(s, y))
            if sy > _CURVATURE_EPS * float(
                    jnp.linalg.norm(s) * jnp.linalg.norm(y)):
                s_hist.insert(0, s)
                y_hist.insert(0, y)
                rho_hist.insert(0, 1.0 / max(sy, _CURVATURE_EPS))
                del s_hist[m:], y_hist[m:], rho_hist[m:]

        pg_new = pgrad(g_new, w_new)
        g_norm = jnp.linalg.norm(pg_new)
        conv = bool(grad_converged(g_norm, jnp.asarray(g0_norm),
                                   config.tolerance)) or bool(
            loss_converged(f_new, f, config.rel_tolerance))
        stalled = not ls_ok   # no measurable decrease possible
        it += 1
        if config.track_states:
            tracker = tracker.record(jnp.asarray(it, jnp.int32),
                                     f_new, g_norm)
        logger.info("streaming lbfgs iter %d: f=%.6f |pg|=%.3e%s", it,
                    float(f_new), float(g_norm),
                    " (stalled)" if stalled else "")
        if ls_ok:
            w, f, g, pg = w_new, f_new, g_new, pg_new
        converged = conv or stalled

    pg_f = pgrad(g, w)
    return OptimizationResult(
        w=w,
        value=f,
        grad_norm=jnp.linalg.norm(pg_f),
        iterations=jnp.asarray(it, jnp.int32),
        converged=jnp.asarray(converged),
        tracker=tracker,
    )
