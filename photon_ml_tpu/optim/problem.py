"""Optimization problems: bind (objective, optimizer, regularization).

Reference counterparts: ``GeneralizedLinearOptimizationProblem`` /
``SingleNodeOptimizationProblem`` / ``DistributedOptimizationProblem``
(photon-api ``com.linkedin.photon.ml.optimization`` [expected paths, mount
unavailable — see SURVEY.md]).

A problem is the solvable unit GAME coordinates hold: it knows which
solver to run (L-BFGS / OWL-QN by L1-presence / TRON), with what config,
against which ``GLMObjective``.  ``run`` is a pure function of
``(batch, w0)`` so:

- the single-node form IS the reference's ``SingleNodeOptimizationProblem``
  (used per-entity under vmap — see ``solve_batched``), and
- the distributed form is the SAME problem whose batch is sharded and whose
  objective psums internally (``photon_ml_tpu.parallel``): unlike the
  reference, no separate Distributed/SingleNode class pair is needed —
  distribution is a property of the data sharding, not the algorithm.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from photon_ml_tpu.data.batch import Batch
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.optim.base import (
    OptimizationResult,
    OptimizerConfig,
    OptimizerType,
)
from photon_ml_tpu.optim.lbfgs import lbfgs_solve
from photon_ml_tpu.optim.tron import tron_solve

Array = jax.Array


@struct.dataclass
class OptimizationProblem:
    """(objective, optimizer type, config) — the solvable unit.

    ``optimizer`` and ``config`` are static; the objective is a pytree
    (its reg/norm arrays trace).  L1 weight lives on the objective's
    ``RegularizationContext`` and routes L-BFGS → OWL-QN automatically,
    mirroring the reference's optimizer selection.
    """

    objective: GLMObjective
    optimizer: OptimizerType = struct.field(
        pytree_node=False, default=OptimizerType.LBFGS
    )
    config: OptimizerConfig = struct.field(
        pytree_node=False, default_factory=OptimizerConfig
    )

    def has_l1(self) -> bool:
        """Concrete L1-presence — decides L-BFGS vs OWL-QN routing.
        Must be evaluated OUTSIDE jit (at problem construction the reg
        weight is a concrete scalar; under trace it is a tracer and the
        routing, being control flow, cannot depend on it)."""
        try:
            return float(self.objective.reg.l1_weight) != 0.0
        except (TypeError, jax.errors.TracerArrayConversionError) as e:
            raise ValueError(
                "has_l1 must be decided on a concrete objective; pass "
                "has_l1= explicitly when calling run() under jit"
            ) from e

    def _l1_vector(self, dim: int) -> Array:
        reg = self.objective.reg
        vec = jnp.broadcast_to(
            jnp.asarray(reg.l1_weight, jnp.float32), (dim,)
        )
        if reg.reg_mask is not None:
            vec = vec * reg.reg_mask
        return vec

    def run(self, batch: Batch, w0: Array,
            has_l1: bool | None = None) -> OptimizationResult:
        """Solve for one batch from one starting point (jittable; when
        called under jit, ``has_l1`` must be supplied — see has_l1)."""
        obj = self.objective
        vg = lambda w: obj.value_and_gradient(w, batch)
        if has_l1 is None:
            has_l1 = self.has_l1()
        if self.optimizer == OptimizerType.TRON:
            if has_l1:
                raise ValueError(
                    "TRON requires a smooth objective; use LBFGS (OWL-QN) "
                    "for L1/elastic-net problems"
                )
            hvp = lambda w, v: obj.hessian_vector(w, v, batch)
            return tron_solve(vg, hvp, w0, self.config)
        l1 = self._l1_vector(w0.shape[-1]) if has_l1 else None
        return lbfgs_solve(vg, w0, self.config, l1_weight=l1)


def solve_batched(
    problem: OptimizationProblem, batches: Batch, w0s: Array
) -> OptimizationResult:
    """vmap ``problem.run`` over stacked problems (leading axis).

    This is the TPU replacement for the reference's per-entity
    ``SingleNodeOptimizationProblem`` loops inside
    ``RandomEffectCoordinate``: ``batches`` holds B same-shape entity
    blocks ([B, n, ...]), ``w0s`` is [B, dim]; each lane converges on its
    own criterion (masked while_loop).  Returns a batched
    ``OptimizationResult`` with leading dim B.
    """
    return jax.vmap(problem.run)(batches, w0s)
