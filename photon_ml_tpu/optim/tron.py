"""TRON: trust-region Newton with a conjugate-gradient inner loop.

Reference counterpart: ``TRON.scala`` (photon-lib
``com.linkedin.photon.ml.optimization``, itself a port of LIBLINEAR's TRON,
Lin & Moré 1999 [expected path, mount unavailable — see SURVEY.md]).

Structure matches the reference algorithm:

- outer loop: Steihaug-CG-solve ``H p = −g`` inside trust radius Δ, take
  the step if the actual/predicted reduction ratio ρ clears η₀, update Δ
  by the standard σ thresholds;
- inner CG: Hessian-vector products only (never a materialized Hessian) —
  on TPU each HVP is the same fused batch pipeline as a gradient, so a CG
  step costs about one extra data pass, exactly the property that made
  TRON attractive on Spark (one treeAggregate per HVP).

Both loops are ``lax.while_loop``s with converged-lane guards, so the
solver is jittable and vmappable (per-entity TRON for random effects).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from photon_ml_tpu.optim.base import (
    Hvp,
    OptimizationResult,
    OptimizerConfig,
    StatesTracker,
    ValueAndGrad,
    grad_converged,
    loss_converged,
)

Array = jax.Array

# LIBLINEAR/Lin-Moré trust-region constants.
_ETA0 = 1e-4   # minimum ρ to accept a step
_SIGMA1 = 0.25  # shrink factor on poor steps
_SIGMA2 = 0.5
_SIGMA3 = 4.0   # growth factor on very good boundary steps
_DELTA_MIN = 1e-12


def _boundary_tau(p: Array, d: Array, delta: Array) -> Array:
    """τ ≥ 0 with ‖p + τ·d‖ = Δ (largest root of the quadratic).

    Numerically hardened for f32 (ISSUE 17): when p already sits on the
    boundary to rounding (‖p‖² ⩾ Δ² by an ulp, which CG's accumulated
    float32 updates produce), ``Δ² − pp`` goes negative-by-epsilon and
    the classic ``(disc − pd)/dd`` numerator cancels catastrophically
    for pd > 0 — the clamped discriminant then yields a small NEGATIVE
    τ, a backward step that exits CG inside the region while reporting
    a boundary hit (and an unguarded discriminant would be NaN, which
    poisons the whole CG carry).  Pick the cancellation-free root form
    per sign(pd) and clamp τ at 0.
    """
    dd = jnp.maximum(jnp.vdot(d, d), 1e-30)
    pd = jnp.vdot(p, d)
    pp = jnp.vdot(p, p)
    gap = delta * delta - pp
    disc = jnp.sqrt(jnp.maximum(pd * pd + dd * gap, 0.0))
    # Largest root of dd·τ² + 2·pd·τ − gap = 0.  The (disc − pd) form
    # subtracts near-equal magnitudes when pd > 0; its conjugate
    # gap/(pd + disc) is exact there and degrades gracefully (τ → 0)
    # when gap underflows negative.
    tau = jnp.where(pd > 0.0,
                    gap / jnp.maximum(pd + disc, 1e-30),
                    (disc - pd) / dd)
    return jnp.maximum(tau, 0.0)


def _steihaug_cg(
    hvp_w, g: Array, delta: Array, config: OptimizerConfig
) -> tuple[Array, Array, Array]:
    """Approximately solve H p = −g within ‖p‖ ≤ Δ.

    Returns (p, hit_boundary, cg_iters).  Stops on the forcing condition
    ‖r‖ ≤ cg_tolerance·‖g‖, the iteration cap, or the trust boundary
    (negative curvature cannot occur for convex GLM objectives but is
    handled identically to the boundary case for safety).
    """
    g_norm = jnp.linalg.norm(g)
    tol = config.cg_tolerance * g_norm

    def cond(state):
        p, r, d, rs, it, done, boundary = state
        return jnp.logical_and(jnp.logical_not(done), it < config.cg_max_iters)

    def body(state):
        p, r, d, rs, it, done, boundary = state
        hd = hvp_w(d)
        dhd = jnp.vdot(d, hd)
        # Negative/zero curvature → march to the boundary along d.
        neg_curv = dhd <= 0.0
        alpha = jnp.where(neg_curv, 0.0, rs / jnp.maximum(dhd, 1e-30))
        p_try = p + alpha * d
        outside = jnp.linalg.norm(p_try) >= delta
        take_boundary = jnp.logical_or(neg_curv, outside)
        tau = _boundary_tau(p, d, delta)
        p_new = jnp.where(take_boundary, p + tau * d, p_try)
        r_new = r - alpha * hd
        rs_new = jnp.vdot(r_new, r_new)
        finished = jnp.logical_or(take_boundary, jnp.sqrt(rs_new) <= tol)
        beta = rs_new / jnp.maximum(rs, 1e-30)
        d_new = r_new + beta * d
        keep = lambda new, old: jnp.where(done, old, new)
        return (
            keep(p_new, p), keep(r_new, r), keep(d_new, d), keep(rs_new, rs),
            keep(it + 1, it),
            jnp.logical_or(done, finished),
            jnp.logical_or(boundary, jnp.logical_and(jnp.logical_not(done),
                                                     take_boundary)),
        )

    p0 = jnp.zeros_like(g)
    r0 = -g
    init = (
        p0, r0, r0, jnp.vdot(r0, r0), jnp.asarray(0, jnp.int32),
        g_norm <= 0.0, jnp.asarray(False),
    )
    p, *_rest = jax.lax.while_loop(cond, body, init)
    boundary = _rest[-1]
    cg_iters = _rest[3]
    return p, boundary, cg_iters


@struct.dataclass
class _TronCarry:
    w: Array
    f: Array
    g: Array
    delta: Array
    iteration: Array
    done: Array
    converged: Array
    g0_norm: Array
    tracker: StatesTracker


def tron_solve(
    value_and_grad: ValueAndGrad,
    hvp: Hvp,
    w0: Array,
    config: OptimizerConfig = OptimizerConfig(),
) -> OptimizationResult:
    """Minimize a twice-differentiable objective by trust-region Newton.

    ``hvp(w, v)`` must return ``H(w)·v`` including the L2 term (the
    objective's ``hessian_vector`` does).  L1 is not supported — the
    reference likewise restricts TRON to smooth objectives.
    """
    f0, g0 = value_and_grad(w0)
    g0_norm = jnp.linalg.norm(g0)

    tracker = StatesTracker.create(config.max_iters)
    if config.track_states:
        tracker = tracker.record(jnp.asarray(0, jnp.int32), f0, g0_norm)

    already = grad_converged(g0_norm, g0_norm, config.tolerance)
    init = _TronCarry(
        w=w0, f=f0, g=g0,
        delta=g0_norm,  # LIBLINEAR's initial radius
        iteration=jnp.asarray(0, jnp.int32),
        done=already, converged=already,
        g0_norm=g0_norm, tracker=tracker,
    )

    def cond(c: _TronCarry):
        return jnp.logical_and(
            jnp.logical_not(c.done), c.iteration < config.max_iters
        )

    def body(c: _TronCarry):
        hvp_w = lambda v: hvp(c.w, v)
        p, _, cg_iters = _steihaug_cg(hvp_w, c.g, c.delta, config)

        f_new, g_new = value_and_grad(c.w + p)
        actual = c.f - f_new
        predicted = -(jnp.vdot(c.g, p) + 0.5 * jnp.vdot(p, hvp_w(p)))
        rho = actual / jnp.maximum(predicted, 1e-30)

        accept = jnp.logical_and(rho > _ETA0, actual > 0.0)
        p_norm = jnp.linalg.norm(p)
        # Radius update (Lin & Moré simplified schedule, as in LIBLINEAR):
        delta = jnp.where(
            rho < _SIGMA1,
            jnp.minimum(c.delta, p_norm) * _SIGMA1,
            jnp.where(rho > 0.75, jnp.maximum(c.delta, _SIGMA3 * p_norm / 2.0),
                      c.delta),
        )
        delta = jnp.maximum(delta, _DELTA_MIN)

        w = jnp.where(accept, c.w + p, c.w)
        f = jnp.where(accept, f_new, c.f)
        g = jnp.where(accept, g_new, c.g)
        g_norm = jnp.linalg.norm(g)

        conv = jnp.logical_or(
            grad_converged(g_norm, c.g0_norm, config.tolerance),
            jnp.logical_and(accept,
                            loss_converged(f_new, c.f, config.rel_tolerance)),
        )
        # Numerical-precision stop: when the model predicts less reduction
        # than float32 can measure on |f|, further iterations only reject
        # steps and shrink Δ — stop and report converged (no measurable
        # progress is possible at this precision).
        precision_floor = 1e-6 * jnp.maximum(jnp.abs(c.f), 1.0)
        numerical_stop = predicted <= precision_floor
        conv = jnp.logical_or(conv, numerical_stop)
        stalled = delta <= _DELTA_MIN
        it = c.iteration + 1
        tracker = (
            c.tracker.record(it, f, g_norm,
                             step_size=jnp.where(accept, p_norm, 0.0),
                             ls_trials=cg_iters)
            if config.track_states else c.tracker
        )

        keep = lambda new, old: jnp.where(c.done, old, new)
        return _TronCarry(
            w=keep(w, c.w), f=keep(f, c.f), g=keep(g, c.g),
            delta=keep(delta, c.delta),
            iteration=keep(it, c.iteration),
            done=jnp.logical_or(c.done, jnp.logical_or(conv, stalled)),
            converged=jnp.logical_or(c.converged, conv),
            g0_norm=c.g0_norm,
            tracker=jax.tree.map(keep, tracker, c.tracker),
        )

    final = jax.lax.while_loop(cond, body, init)
    return OptimizationResult(
        w=final.w,
        value=final.f,
        grad_norm=jnp.linalg.norm(final.g),
        iterations=final.iteration,
        converged=final.converged,
        tracker=final.tracker,
    )
