"""L-BFGS and OWL-QN as pure-JAX ``lax.while_loop`` solvers.

Reference counterparts: ``LBFGS.scala`` / ``OWLQN.scala`` (photon-lib
``com.linkedin.photon.ml.optimization``, thin wrappers over Breeze's
``LBFGS``/``OWLQN`` [expected paths, mount unavailable — see SURVEY.md]).

TPU-native design notes:

- The two-loop recursion runs over a **fixed-size circular buffer** of
  (s, y) pairs ([m, dim] arrays) with masking for unfilled slots — static
  shapes, so one compilation serves every iteration, and ``vmap`` batches
  the buffers over problems.
- Line search is backtracking Armijo (sufficient decrease) with a curvature
  skip-guard on the (s, y) update (``sᵀy > ε‖s‖‖y‖``) in place of Breeze's
  strong-Wolfe search: same convergence class on convex GLM objectives,
  far simpler under jit/vmap (no data-dependent bracketing structure).
- **OWL-QN is the same loop** with three hooks switched on when an L1
  weight is present, exactly the Breeze specialization structure:
  (1) the *pseudo-gradient* replaces the gradient in direction finding and
  convergence, (2) the search direction is projected onto the
  pseudo-gradient's descent orthant, (3) line-search iterates are projected
  onto the starting orthant and scored with the L1-inclusive objective.
  Curvature pairs use smooth gradients, as in Breeze.
- Every update is guarded by ``done`` so converged vmap lanes coast (see
  optim.base docstring).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from photon_ml_tpu.optim.base import (
    OptimizationResult,
    OptimizerConfig,
    StatesTracker,
    ValueAndGrad,
    grad_converged,
    loss_converged,
)

Array = jax.Array

_CURVATURE_EPS = 1e-10


@struct.dataclass
class _LbfgsCarry:
    w: Array          # [d]
    f: Array          # scalar — L1-inclusive value for OWL-QN
    g: Array          # [d] smooth gradient
    s_buf: Array      # [m, d] position diffs, circular
    y_buf: Array      # [m, d] gradient diffs, circular
    rho_buf: Array    # [m] 1/(sᵀy)
    head: Array       # int32 — next insert slot
    count: Array      # int32 — valid pairs (≤ m)
    iteration: Array  # int32
    done: Array       # bool — this lane finished (converged or stalled)
    converged: Array  # bool — finished due to tolerance
    g0_norm: Array    # scalar — initial gradient norm (for rel. tolerance)
    tracker: StatesTracker


def _pseudo_gradient(g: Array, w: Array, l1: Array) -> Array:
    """OWL-QN pseudo-gradient of f(w) + ‖l1 ⊙ w‖₁ (Andrew & Gao 2007).

    For w_j ≠ 0 the L1 term is differentiable; at w_j = 0 pick the one-sided
    derivative that points downhill, or 0 inside the subdifferential.
    """
    g_plus = g + l1
    g_minus = g - l1
    return jnp.where(
        w > 0.0,
        g_plus,
        jnp.where(
            w < 0.0,
            g_minus,
            jnp.where(g_minus > 0.0, g_minus, jnp.where(g_plus < 0.0, g_plus, 0.0)),
        ),
    )


def _two_loop(g_dir: Array, carry: _LbfgsCarry, m: int) -> Array:
    """Two-loop recursion over the circular (s, y) buffer → descent dir.

    Slot ages: pair j (0 = newest) lives at index (head − 1 − j) mod m.
    Masked for j ≥ count; with count == 0 this degrades to steepest descent.
    """
    q = g_dir

    def bwd(j, val):
        q, alphas = val
        idx = (carry.head - 1 - j) % m
        valid = j < carry.count
        alpha = carry.rho_buf[idx] * jnp.vdot(carry.s_buf[idx], q)
        alpha = jnp.where(valid, alpha, 0.0)
        q = q - alpha * carry.y_buf[idx]
        return q, alphas.at[j].set(alpha)

    q, alphas = jax.lax.fori_loop(
        0, m, bwd, (q, jnp.zeros((m,), g_dir.dtype))
    )

    # Initial Hessian scaling γ = sᵀy / yᵀy of the newest pair.
    newest = (carry.head - 1) % m
    y_new = carry.y_buf[newest]
    gamma = jnp.where(
        carry.count > 0,
        1.0 / jnp.maximum(carry.rho_buf[newest] * jnp.vdot(y_new, y_new),
                          _CURVATURE_EPS),
        1.0,
    )
    r = gamma * q

    def fwd(j_rev, r):
        j = m - 1 - j_rev  # oldest → newest
        idx = (carry.head - 1 - j) % m
        valid = j < carry.count
        beta = carry.rho_buf[idx] * jnp.vdot(carry.y_buf[idx], r)
        upd = carry.s_buf[idx] * (alphas[j] - beta)
        return r + jnp.where(valid, upd, 0.0)

    r = jax.lax.fori_loop(0, m, fwd, r)
    return -r


def _orthant(w: Array, pg: Array) -> Array:
    """OWL-QN search orthant ξ: sign(w), or sign(−pg) where w = 0."""
    return jnp.where(w != 0.0, jnp.sign(w), jnp.sign(-pg))


def _line_search(
    value_fn, w: Array, f0: Array, pg: Array, d: Array,
    config: OptimizerConfig, xi: Array | None,
) -> tuple[Array, Array, Array, Array, Array]:
    """Backtracking Armijo; returns (w_new, f_new, ok, alpha, trials).

    Sufficient-decrease test (Andrew & Gao's modified condition, which
    reduces to standard Armijo when there is no orthant projection):

        f(x⁺) ≤ f(x) + c1 · pgᵀ(x⁺ − x),   x⁺ = π(x + α·d; ξ)

    For OWL-QN (``xi`` given) trial points are projected onto the starting
    orthant and the slope uses the *actual* displacement x⁺ − x (which may
    differ from α·d where coordinates were clipped to zero).
    """

    def trial(alpha):
        w_try = w + alpha * d
        if xi is not None:
            w_try = jnp.where(jnp.sign(w_try) == xi, w_try, 0.0)
        return w_try, value_fn(w_try)

    def accepts(w_try, f_try):
        return f_try <= f0 + config.ls_c1 * jnp.vdot(pg, w_try - w)

    def cond(state):
        _, w_try, f_try, steps = state
        return jnp.logical_and(
            jnp.logical_not(accepts(w_try, f_try)),
            steps < config.ls_max_steps,
        )

    def body(state):
        alpha, _, _, steps = state
        alpha = alpha * config.ls_shrink
        w_try, f_try = trial(alpha)
        return alpha, w_try, f_try, steps + 1

    alpha0 = jnp.asarray(1.0, w.dtype)
    w1, f1 = trial(alpha0)
    alpha, w_new, f_new, steps = jax.lax.while_loop(
        cond, body, (alpha0, w1, f1, jnp.asarray(0, jnp.int32))
    )
    ok = f_new < f0  # any strict decrease counts; stall otherwise
    return w_new, f_new, ok, alpha, steps + 1


def lbfgs_solve(
    value_and_grad: ValueAndGrad,
    w0: Array,
    config: OptimizerConfig = OptimizerConfig(),
    l1_weight: Array | None = None,
) -> OptimizationResult:
    """Minimize a smooth objective (plus optional L1 term → OWL-QN).

    Args:
      value_and_grad: smooth part — ``w → (f_smooth, ∇f_smooth)``.  The L1
        term must NOT be folded in; pass it via ``l1_weight``.
      w0: [dim] initial point.
      l1_weight: None (plain L-BFGS) or per-coordinate L1 weights [dim]
        (scalars broadcast), activating OWL-QN semantics.

    Jittable; vmap over (w0, closed-over batch) solves many problems at
    once with per-lane convergence.
    """
    m = config.lbfgs_memory
    d = w0.shape[-1]
    owlqn = l1_weight is not None
    if owlqn:
        l1_vec = jnp.broadcast_to(jnp.asarray(l1_weight, w0.dtype), (d,))

    def full_value(w):
        f, _ = value_and_grad(w)
        return f + jnp.sum(l1_vec * jnp.abs(w)) if owlqn else f

    f0_s, g0 = value_and_grad(w0)
    f0 = f0_s + jnp.sum(l1_vec * jnp.abs(w0)) if owlqn else f0_s
    pg0 = _pseudo_gradient(g0, w0, l1_vec) if owlqn else g0
    g0_norm = jnp.linalg.norm(pg0)

    tracker = StatesTracker.create(config.max_iters)
    if config.track_states:
        tracker = tracker.record(jnp.asarray(0, jnp.int32), f0, g0_norm)

    already = grad_converged(g0_norm, g0_norm, config.tolerance)
    init = _LbfgsCarry(
        w=w0, f=f0, g=g0,
        s_buf=jnp.zeros((m, d), w0.dtype),
        y_buf=jnp.zeros((m, d), w0.dtype),
        rho_buf=jnp.zeros((m,), w0.dtype),
        head=jnp.asarray(0, jnp.int32),
        count=jnp.asarray(0, jnp.int32),
        iteration=jnp.asarray(0, jnp.int32),
        done=already,
        converged=already,
        g0_norm=g0_norm,
        tracker=tracker,
    )

    def cond(c: _LbfgsCarry):
        return jnp.logical_and(
            jnp.logical_not(c.done), c.iteration < config.max_iters
        )

    def body(c: _LbfgsCarry):
        pg = _pseudo_gradient(c.g, c.w, l1_vec) if owlqn else c.g
        d_dir = _two_loop(pg, c, m)
        if owlqn:
            # Constrain to the pseudo-gradient's descent orthant.
            d_dir = jnp.where(d_dir * -pg > 0.0, d_dir, 0.0)
            xi = _orthant(c.w, pg)
        else:
            xi = None
        # Safeguard: if not a descent direction (numerical breakdown),
        # restart from steepest descent.
        bad = jnp.vdot(pg, d_dir) >= 0.0
        d_dir = jnp.where(bad, -pg, d_dir)

        w_new, f_new, ls_ok, alpha, trials = _line_search(
            full_value, c.w, c.f, pg, d_dir, config, xi
        )
        f_s_new, g_new = value_and_grad(w_new)

        s = w_new - c.w
        y = g_new - c.g
        sy = jnp.vdot(s, y)
        good_pair = jnp.logical_and(
            ls_ok, sy > _CURVATURE_EPS * jnp.linalg.norm(s) * jnp.linalg.norm(y)
        )
        s_buf = jnp.where(good_pair, c.s_buf.at[c.head].set(s), c.s_buf)
        y_buf = jnp.where(good_pair, c.y_buf.at[c.head].set(y), c.y_buf)
        rho_buf = jnp.where(
            good_pair,
            c.rho_buf.at[c.head].set(1.0 / jnp.maximum(sy, _CURVATURE_EPS)),
            c.rho_buf,
        )
        head = jnp.where(good_pair, (c.head + 1) % m, c.head)
        count = jnp.where(good_pair, jnp.minimum(c.count + 1, m), c.count)

        pg_new = _pseudo_gradient(g_new, w_new, l1_vec) if owlqn else g_new
        g_norm = jnp.linalg.norm(pg_new)
        conv = jnp.logical_or(
            grad_converged(g_norm, c.g0_norm, config.tolerance),
            loss_converged(f_new, c.f, config.rel_tolerance),
        )
        # A full backtracking failure on a guaranteed descent direction
        # (the steepest-descent safeguard above) means the decrease is
        # below float32 measurement precision — report converged, since no
        # measurable progress is possible (Breeze similarly terminates on
        # LineSearchFailed and returns the current state).
        stalled = jnp.logical_not(ls_ok)
        conv = jnp.logical_or(conv, stalled)
        it = c.iteration + 1

        tracker = (
            c.tracker.record(it, f_new, g_norm,
                             step_size=jnp.where(ls_ok, alpha, 0.0),
                             ls_trials=trials)
            if config.track_states
            else c.tracker
        )

        # Converged-lane guard: if already done (only reachable under vmap
        # races), keep old state; otherwise commit.
        def keep(new, old):
            return jnp.where(c.done, old, new)

        return _LbfgsCarry(
            w=keep(jnp.where(ls_ok, w_new, c.w), c.w),
            f=keep(jnp.where(ls_ok, f_new, c.f), c.f),
            g=keep(jnp.where(ls_ok, g_new, c.g), c.g),
            s_buf=keep(s_buf, c.s_buf),
            y_buf=keep(y_buf, c.y_buf),
            rho_buf=keep(rho_buf, c.rho_buf),
            head=keep(head, c.head),
            count=keep(count, c.count),
            iteration=keep(it, c.iteration),
            done=jnp.logical_or(c.done, jnp.logical_or(conv, stalled)),
            converged=jnp.logical_or(c.converged, conv),
            g0_norm=c.g0_norm,
            tracker=jax.tree.map(keep, tracker, c.tracker),
        )

    final = jax.lax.while_loop(cond, body, init)
    pg_f = _pseudo_gradient(final.g, final.w, l1_vec) if owlqn else final.g
    return OptimizationResult(
        w=final.w,
        value=final.f,
        grad_norm=jnp.linalg.norm(pg_f),
        iterations=final.iteration,
        converged=final.converged,
        tracker=final.tracker,
    )


def owlqn_solve(
    value_and_grad: ValueAndGrad,
    w0: Array,
    l1_weight: Array,
    config: OptimizerConfig = OptimizerConfig(),
) -> OptimizationResult:
    """OWL-QN = L-BFGS with orthant-wise L1 handling (reference ``OWLQN``)."""
    return lbfgs_solve(value_and_grad, w0, config, l1_weight=l1_weight)


def lbfgs_solve_swept(
    value_and_grad,
    w0s: Array,
    lane_ctx,
    config: OptimizerConfig = OptimizerConfig(),
    l1_weights: Array | None = None,
    use_map: bool = False,
) -> OptimizationResult:
    """Batched masked-lane L-BFGS / OWL-QN over L concurrent problems.

    The λ-sweep entry: one solve drives every grid point at once, so
    each objective evaluation inside the ``while_loop`` serves all L
    coefficient lanes against the SAME closed-over batch — one data
    stream amortized across the grid.  This is the proven
    masked-``while_loop`` vmap pattern of the random-effects bucket
    path (``game.coordinates._re_train_impl``): converged lanes coast
    under their ``done`` guard while stragglers finish.

    Args:
      value_and_grad: per-lane smooth objective
        ``(w [dim], lane_ctx_l) → (f, g)``; per-lane parameters (the
        lane's L2 weight, typically) ride in ``lane_ctx``.
      w0s: [L, dim] stacked starting points.
      lane_ctx: pytree whose leaves have leading axis L; row l is
        passed to ``value_and_grad`` for lane l.
      l1_weights: None (plain L-BFGS) or per-lane L1 weights — [L]
        scalars or [L, dim] vectors — activating OWL-QN semantics on
        EVERY lane (a zero row degrades to an all-zero l1 vector).
      use_map: run the lane axis as a ``lax.map`` loop instead of
        ``vmap`` — for objectives with no batching rule (GRR Pallas
        kernel, shard_mapped distributed objectives).  Still one
        compiled program over the whole grid; the amortization is then
        HBM-residency rather than a shared read.
    """
    if l1_weights is not None:
        def lane(args):
            w0, ctx, l1 = args
            return lbfgs_solve(lambda w: value_and_grad(w, ctx), w0,
                               config, l1_weight=l1)
        xs = (w0s, lane_ctx, l1_weights)
    else:
        def lane(args):
            w0, ctx = args
            return lbfgs_solve(lambda w: value_and_grad(w, ctx), w0, config)
        xs = (w0s, lane_ctx)
    if use_map:
        return jax.lax.map(lane, xs)
    return jax.vmap(lane)(xs)


def owlqn_solve_swept(
    value_and_grad,
    w0s: Array,
    lane_ctx,
    l1_weights: Array,
    config: OptimizerConfig = OptimizerConfig(),
    use_map: bool = False,
) -> OptimizationResult:
    """Batched-lane OWL-QN (see ``lbfgs_solve_swept``)."""
    return lbfgs_solve_swept(value_and_grad, w0s, lane_ctx, config,
                             l1_weights=l1_weights, use_map=use_map)
