"""Coefficient variance computation from the Hessian at the optimum.

Reference counterparts: ``VarianceComputationType`` (NONE/SIMPLE/FULL)
and the variance path of ``GeneralizedLinearOptimizationProblem``
(photon-api ``com.linkedin.photon.ml.optimization`` [expected paths,
mount unavailable — see SURVEY.md §2.1]):

- SIMPLE: var_j = 1 / H_jj — the reciprocal of the Hessian diagonal
  (one fused aggregation pass, reference ``HessianDiagonalAggregator``);
- FULL:   var_j = (H⁻¹)_jj — the diagonal of the inverse Hessian.

TPU design: SIMPLE is a single ``hessian_diagonal`` kernel call.  FULL
materializes H column-by-column with ``vmap``ped Hessian-vector products
against the identity (d HVPs fused into one batched device program — an
MXU-friendly [d, d] build) and Cholesky-solves for the inverse diagonal.
FULL is meant for the fixed effect at GLM dims (the reference likewise
reserves it for modest feature counts); per-entity variances under
``vmap`` use SIMPLE.
"""

from __future__ import annotations

import enum

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.batch import Batch
from photon_ml_tpu.ops.objective import GLMObjective

Array = jax.Array


class VarianceComputationType(str, enum.Enum):
    NONE = "NONE"
    SIMPLE = "SIMPLE"
    FULL = "FULL"


def simple_variances(obj: GLMObjective, w: Array, batch: Batch) -> Array:
    """1 / diag(H) at w (jittable, vmappable)."""
    diag = obj.hessian_diagonal(w, batch)
    return 1.0 / jnp.maximum(diag, 1e-12)


def materialize_hessian(obj: GLMObjective, w: Array, batch: Batch) -> Array:
    """[d, d] Hessian via batched HVPs against identity columns."""
    dim = w.shape[-1]
    eye = jnp.eye(dim, dtype=w.dtype)
    return jax.vmap(lambda v: obj.hessian_vector(w, v, batch))(eye)


def full_variances(obj: GLMObjective, w: Array, batch: Batch) -> Array:
    """diag(H⁻¹) at w via Cholesky (H is SPD for convex GLM + L2)."""
    h = materialize_hessian(obj, w, batch)
    dim = w.shape[-1]
    # Tiny jitter keeps the factorization stable when unregularized
    # directions are nearly flat (reference relies on Breeze's solve).
    chol = jax.scipy.linalg.cho_factor(h + 1e-8 * jnp.eye(dim, dtype=w.dtype))
    inv = jax.scipy.linalg.cho_solve(chol, jnp.eye(dim, dtype=w.dtype))
    return jnp.diagonal(inv)


def compute_variances(
    obj: GLMObjective,
    w: Array,
    batch: Batch,
    variance_type: VarianceComputationType,
) -> Array | None:
    if variance_type == VarianceComputationType.NONE:
        return None
    if variance_type == VarianceComputationType.SIMPLE:
        return simple_variances(obj, w, batch)
    return full_variances(obj, w, batch)
