"""Static-shape example batches: the TPU-native replacement for RDD[LabeledPoint].

Reference counterpart: ``LabeledPoint`` / per-partition ``Iterable[LabeledPoint]``
(photon-api ``com.linkedin.photon.ml.data`` [expected path, mount unavailable —
see SURVEY.md]).  The reference streams sparse Breeze vectors through a Scala
fold; on TPU we instead materialize a whole (shard of a) dataset as one
static-shape array bundle resident in HBM, so every optimizer iteration is a
handful of fused XLA ops with zero host involvement.

Two layouts:

- ``DenseBatch`` — ``x: [n, d]`` dense features.  Best when d is small
  (a1a: d=124) — margins are one MXU matmul.
- ``SparseBatch`` — padded ELL layout: ``values/col_ids: [n, k]`` where k is
  the per-row nnz capacity (max nnz, possibly bucketed).  ELL keeps shapes
  static (XLA requirement) while storing only k·n entries of a d-wide matrix;
  margins are a gather + row-sum, gradients a segment-sum scatter.  This is
  the TPU answer to Breeze's SparseVector: no CSR row_ptr indirection, which
  would force dynamic slicing inside jit.

Both carry per-example ``labels, weights, offsets`` (offsets implement GAME
coordinate-descent residual passing, reference ``GameDatum.offset``) and a
validity ``mask`` so padding rows contribute zero loss/gradient.

All fields are pytree leaves → batches can be donated, sharded with
``jax.sharding``, and closed over by jit.  ``dim`` is static metadata.
"""

from __future__ import annotations

from typing import Union

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from photon_ml_tpu.data.colmajor import ColMajorSlice, build_colmajor
from photon_ml_tpu.data.grr import GrrPair, build_grr_pair

Array = jax.Array


@struct.dataclass
class DenseBatch:
    """Dense feature batch; ``x[i]`` is example i's feature vector."""

    x: Array          # [n, d] float
    labels: Array     # [n] float
    weights: Array    # [n] float
    offsets: Array    # [n] float
    mask: Array       # [n] float, 1.0 = real example, 0.0 = padding

    @property
    def dim(self) -> int:
        return self.x.shape[-1]

    @property
    def n_padded(self) -> int:
        return self.x.shape[-2]

    def margins(self, w: Array) -> Array:
        """x·w + offset, the GLM margin (one MXU matmul)."""
        return self.x @ w + self.offsets

    def xt_dot(self, r: Array) -> Array:
        """X^T r — gradient-side contraction (masking folded into r)."""
        return self.x.T @ r

    def x_dot(self, v: Array) -> Array:
        """X v — HVP-side contraction."""
        return self.x @ v


@struct.dataclass
class SparseBatch:
    """Padded-ELL sparse batch.

    ``col_ids`` padding entries point at column 0 with ``values`` 0.0 so
    gathers stay in-bounds and scatters add zero; correctness never depends
    on the padding target.

    Layout variants for the two contractions (margins X·w, gradient Xᵀr):

    - ``grr`` (``data.grr.GrrPair``, build with ``make_sparse_batch(...,
      grr=True)``): the production TPU path — both directions compiled
      into the gather-route-reduce plan executed by a Mosaic kernel at
      vector speed, with hot columns on the MXU.  ~100× faster than the
      XLA formulations on v5e.
    - ``colmajor`` (``data.colmajor``): transposed-ELL copy making Xᵀr
      a gather+segment-fold instead of a full scatter.  Still pays
      XLA's scalar gather on TPU; useful as the mesh-shardable layout
      and on CPU.
    - neither: plain ELL — margins via XLA gather, Xᵀr via
      ``segment_sum`` scatter.  Fine for small batches and tests.
    """

    values: Array     # [n, k] float
    col_ids: Array    # [n, k] int32
    labels: Array     # [n] float
    weights: Array    # [n] float
    offsets: Array    # [n] float
    mask: Array       # [n] float
    dim: int = struct.field(pytree_node=False)
    colmajor: "ColMajorSlice | None" = None
    grr: "GrrPair | None" = None

    @property
    def n_padded(self) -> int:
        return self.values.shape[-2]

    def margins(self, w: Array) -> Array:
        """Σ_k values[i,k]·w[col_ids[i,k]] + offset."""
        if self.grr is not None:
            return self.grr.dot(w) + self.offsets
        from photon_ml_tpu.ops.kernels import gather_rowsum

        return gather_rowsum(w, self.values, self.col_ids) + self.offsets

    def xt_dot(self, r: Array) -> Array:
        """X^T r — GRR kernel, else transposed-ELL gather, else a
        segment-sum scatter-add into the [dim] gradient."""
        if self.grr is not None:
            return self.grr.t_dot(r)
        if self.colmajor is not None:
            return self.colmajor.xt_dot(r)
        contrib = self.values * r[:, None]            # [n, k]
        return jax.ops.segment_sum(
            contrib.reshape(-1),
            self.col_ids.reshape(-1),
            num_segments=self.dim,
        )

    def x_dot(self, v: Array) -> Array:
        if self.grr is not None:
            return self.grr.dot(v)
        from photon_ml_tpu.ops.kernels import gather_rowsum

        return gather_rowsum(v, self.values, self.col_ids)

    def to_dense(self) -> DenseBatch:
        """Densify (testing / small-dim fast path)."""
        n, k = self.values.shape
        x = jnp.zeros((n, self.dim), self.values.dtype)
        rows = jnp.repeat(jnp.arange(n), k)
        x = x.at[rows, self.col_ids.reshape(-1)].add(self.values.reshape(-1))
        return DenseBatch(
            x=x, labels=self.labels, weights=self.weights,
            offsets=self.offsets, mask=self.mask,
        )


Batch = Union[DenseBatch, SparseBatch]


def make_dense_batch(
    x: np.ndarray,
    labels: np.ndarray,
    weights: np.ndarray | None = None,
    offsets: np.ndarray | None = None,
    pad_to: int | None = None,
    dtype=jnp.float32,
) -> DenseBatch:
    """Build a DenseBatch from host arrays, padding rows to ``pad_to``."""
    n, _ = x.shape
    weights = np.ones(n) if weights is None else weights
    offsets = np.zeros(n) if offsets is None else offsets
    mask = np.ones(n)
    if pad_to is not None and pad_to > n:
        pad = pad_to - n
        x = np.pad(x, ((0, pad), (0, 0)))
        labels = np.pad(labels, (0, pad))
        weights = np.pad(weights, (0, pad))
        offsets = np.pad(offsets, (0, pad))
        mask = np.pad(mask, (0, pad))
    return DenseBatch(
        x=jnp.asarray(x, dtype),
        labels=jnp.asarray(labels, dtype),
        weights=jnp.asarray(weights, dtype),
        offsets=jnp.asarray(offsets, dtype),
        mask=jnp.asarray(mask, dtype),
    )


def make_sparse_batch(
    rows: list[tuple[np.ndarray, np.ndarray]],
    dim: int,
    labels: np.ndarray,
    weights: np.ndarray | None = None,
    offsets: np.ndarray | None = None,
    row_capacity: int | None = None,
    pad_to: int | None = None,
    dtype=jnp.float32,
    col_major: bool = False,
    col_capacity: int | None = None,
    grr: bool = False,
    keep_ell: bool = True,
    cache_dir: str | None = None,
) -> SparseBatch:
    """Build a padded-ELL SparseBatch.

    Args:
      rows: per-example ``(col_ids, values)`` numpy pairs.
      dim: feature-space width (static).
      row_capacity: per-row nnz capacity; defaults to the max observed.
      pad_to: pad the example count to this (e.g. a multiple of shard count).
      col_major: also build the transposed-ELL copy so gradients run
        without the full-size scatter (see ``data.colmajor``).
      col_capacity: virtual-row capacity for the transpose (default:
        auto from the column-occupancy distribution).
      grr: compile the GRR plan (``data.grr``) — the fast TPU path for
        both contraction directions; supersedes ``col_major`` when set.
      cache_dir: on-disk GRR plan cache directory (see
        ``photon_ml_tpu.cache``) — a second build of the same data and
        options loads the plan instead of re-deriving it.
      keep_ell: with ``grr``, whether the ELL arrays also go to device.
        The GRR plan serves every contraction, so the device ELL copy
        (8 bytes/nnz of HBM) is only needed by feature statistics /
        normalization and the down-sampled training view; scale runs
        that use neither pass False and the batch stores zero-width
        [n, 0] placeholders instead (SURVEY §7 scale class).
    """
    from photon_ml_tpu.data.sparse_rows import SparseRows

    n = len(rows)
    if isinstance(rows, SparseRows):
        # Scale path: canonical CSR → ELL in one vectorized scatter.
        # Canonical form already guarantees unique sorted per-row ids
        # (the invariant hessian_diagonal needs).
        k = max(row_capacity or rows.max_nnz, 1)
        n_out = max(pad_to or n, n)
        cols, vals = rows.to_ell(row_capacity=k, pad_to=n_out)
    else:
        k = row_capacity or max((len(c) for c, _ in rows), default=1)
        k = max(k, 1)
        n_out = max(pad_to or n, n)
        vals = np.zeros((n_out, k), np.float32)
        cols = np.zeros((n_out, k), np.int32)
        for i, (c, v) in enumerate(rows):
            if len(c) > k:
                raise ValueError(
                    f"row {i} nnz {len(c)} exceeds capacity {k}")
            # Duplicate column ids within a row would silently break
            # hessian_diagonal (which squares values elementwise, so
            # duplicates give Σv² instead of (Σv)²); reject them at
            # construction time.
            if len(np.unique(c)) != len(c):
                raise ValueError(
                    f"row {i} has duplicate column ids; SparseBatch "
                    "requires unique col_ids per row (pre-sum duplicates "
                    "on the host)"
                )
            vals[i, : len(c)] = v
            cols[i, : len(c)] = c
    weights = np.ones(n) if weights is None else np.asarray(weights)
    offsets = np.zeros(n) if offsets is None else np.asarray(offsets)
    lab = np.zeros(n_out)
    lab[:n] = labels
    wt = np.zeros(n_out)
    wt[:n] = weights
    off = np.zeros(n_out)
    off[:n] = offsets
    mask = np.zeros(n_out)
    mask[:n] = 1.0
    cm = (
        build_colmajor(cols, vals, dim, capacity=col_capacity)
        if col_major and not grr
        else None
    )
    pair = (build_grr_pair(cols, vals, dim, cache_dir=cache_dir)
            if grr else None)
    if grr and not keep_ell:
        vals = np.zeros((n_out, 0), np.float32)
        cols = np.zeros((n_out, 0), np.int32)
    return SparseBatch(
        values=jnp.asarray(vals, dtype),
        col_ids=jnp.asarray(cols),
        labels=jnp.asarray(lab, dtype),
        weights=jnp.asarray(wt, dtype),
        offsets=jnp.asarray(off, dtype),
        mask=jnp.asarray(mask, dtype),
        dim=dim,
        colmajor=cm,
        grr=pair,
    )
