"""Per-feature summary statistics over a dataset.

Reference counterpart: ``FeatureDataStatistics`` /
``BasicStatisticalSummary`` (photon-api
``com.linkedin.photon.ml.stat`` [expected path, mount unavailable — see
SURVEY.md]) — computed there by a Spark aggregation; here by a single
jitted pass of masked reductions over the batch (or a psum-reduced pass
over shards via the distributed objective's mesh — the stats are plain
sums, so sharding composes trivially).

These feed ``compute_normalization`` (SURVEY §2.4): mean/std for
standardization, max|x| for max-magnitude scaling.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from photon_ml_tpu.data.batch import Batch, DenseBatch, SparseBatch

Array = jax.Array


@struct.dataclass
class FeatureStatistics:
    """Per-feature [dim] summaries over the *unweighted* examples
    (matching the reference, which summarizes raw features)."""

    count: Array      # scalar — number of (real) examples
    mean: Array       # [dim]
    variance: Array   # [dim] (population variance, as Spark's Summarizer)
    std: Array        # [dim]
    min: Array        # [dim]
    max: Array        # [dim]
    max_abs: Array    # [dim]
    num_nonzeros: Array  # [dim]

    @property
    def dim(self) -> int:
        return self.mean.shape[-1]


def compute_statistics(batch: Batch) -> FeatureStatistics:
    """One pass of masked reductions → FeatureStatistics (jittable).

    Sparse batches are summarized without densification: sums and
    sums-of-squares come from segment-sums over the ELL entries; min/max
    account for implicit zeros (a feature absent from some rows has
    min ≤ 0 ≤ max contributions from those rows).
    """
    mask = batch.mask
    n = jnp.sum(mask)
    dim = batch.dim

    if isinstance(batch, DenseBatch):
        xm = batch.x * mask[:, None]
        s1 = jnp.sum(xm, axis=0)
        s2 = jnp.sum(xm * batch.x, axis=0)
        nnz = jnp.sum((batch.x != 0.0) & (mask[:, None] > 0.0), axis=0)
        # Masked rows must not affect min/max: substitute +inf/−inf.
        big = jnp.inf
        x_min = jnp.min(jnp.where(mask[:, None] > 0.0, batch.x, big), axis=0)
        x_max = jnp.max(jnp.where(mask[:, None] > 0.0, batch.x, -big), axis=0)
    else:
        assert isinstance(batch, SparseBatch)
        vm = batch.values * mask[:, None]
        cols = batch.col_ids.reshape(-1)
        s1 = jax.ops.segment_sum(vm.reshape(-1), cols, num_segments=dim)
        s2 = jax.ops.segment_sum(
            (vm * batch.values).reshape(-1), cols, num_segments=dim
        )
        real_entry = ((batch.values != 0.0) & (mask[:, None] > 0.0))
        nnz = jax.ops.segment_sum(
            real_entry.astype(jnp.float32).reshape(-1), cols, num_segments=dim
        )
        # Explicit-entry extrema; zero-fill features with implicit zeros.
        big = jnp.asarray(jnp.inf, batch.values.dtype)
        v_min_entries = jnp.where(real_entry, batch.values, big).reshape(-1)
        v_max_entries = jnp.where(real_entry, batch.values, -big).reshape(-1)
        x_min = jax.ops.segment_min(v_min_entries, cols, num_segments=dim)
        x_max = jax.ops.segment_max(v_max_entries, cols, num_segments=dim)
        # A feature with fewer explicit entries than examples has implicit
        # zeros → extrema must include 0.
        has_implicit_zero = nnz < n
        x_min = jnp.where(has_implicit_zero, jnp.minimum(x_min, 0.0), x_min)
        x_max = jnp.where(has_implicit_zero, jnp.maximum(x_max, 0.0), x_max)

    # Unseen features (all-padding columns): clean zeros, not ±inf.
    x_min = jnp.where(jnp.isfinite(x_min), x_min, 0.0)
    x_max = jnp.where(jnp.isfinite(x_max), x_max, 0.0)

    n_safe = jnp.maximum(n, 1.0)
    mean = s1 / n_safe
    var = jnp.maximum(s2 / n_safe - mean * mean, 0.0)
    return FeatureStatistics(
        count=n,
        mean=mean,
        variance=var,
        std=jnp.sqrt(var),
        min=x_min,
        max=x_max,
        max_abs=jnp.maximum(jnp.abs(x_min), jnp.abs(x_max)),
        num_nonzeros=nnz,
    )
