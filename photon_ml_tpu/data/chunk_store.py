"""Disk-backed chunk store: the third tier under ``data.chunked_batch``.

Reference counterpart: Spark's disk-spillable RDD persistence — a
partition that does not fit the executor heap spills to local disk and
is re-read (or recomputed from lineage) on the next pass, so the
trainable size is bounded by cluster DISK, not RAM (SURVEY §1 L1).
Round 5 removed the HBM residency cap by streaming compiled chunk
batches through the device, but every chunk still lived in host RAM
(26.4 GB RSS at 3×10⁷ examples) — the KDD2012 shape (1.5×10⁸) had no
single-host path.  Snap ML reaches datasets 10× beyond RAM with the
same three-tier pattern this module completes: NVMe/disk → host
staging window → accelerator, with prefetch overlapping every tier
(PAPERS.md).

Design:

- **One atomic ``.npz`` per chunk** under ``<spill_dir>/chunks/``,
  written with the plan cache's tmp+``os.replace`` primitive
  (``cache.plan_cache.atomic_savez``) and keyed by a blake2b content
  fingerprint of the exact build inputs × the build configuration ×
  a format version — so a spilled dataset doubles as a persistent
  warm-ETL artifact: the next run with the same data skips the chunk
  compile entirely.  Offsets are NOT part of the payload (they change
  every GAME coordinate-descent iteration); ``ChunkedBatch`` overlays
  the current offsets window at access time, so spilled files stay
  valid across CD sweeps and across runs.
- **Memory-mapped loads**: ``np.savez`` members are STORED (never
  deflated), i.e. each member is a whole ``.npy`` at a knowable file
  offset — ``_open_npz_mmap`` parses the zip local headers and hands
  back ``np.memmap`` views, so a loaded chunk costs address space and
  page-cache traffic, not anonymous RSS, and the OS can reclaim clean
  pages under pressure.  Any parse surprise falls back to a plain
  ``np.load`` copy; any read failure falls back to a rebuild — the
  store must never be able to make a run fail (plan-cache rule).
- **LRU host window**: at most ``host_max_resident`` decoded chunks
  stay live; admission evicts the least-recently-used first, and
  eviction is a reference drop (numpy/memmap frees follow refcounts,
  so an in-flight ``device_put`` holding a reference is always safe).
- **Reader accounting**: the streaming prefetch thread registers as a
  reader (``begin_read``/``end_read``);
  ``ChunkedGLMObjective.invalidate`` asserts the store is quiesced
  (``assert_quiesced``) before dropping buffers, so a use-after-evict
  race is a loud error, not a corruption.
"""

from __future__ import annotations

import errno
import hashlib
import json
import logging
import os
import shutil
import struct
import threading
import zipfile
from collections import OrderedDict

import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.reliability import faults as _faults
from photon_ml_tpu.reliability import retry as _retry

logger = logging.getLogger(__name__)

# On-disk chunk format version: bump when the member layout changes —
# the version rides in the file NAME, so stale entries are clean misses.
CHUNK_FORMAT_VERSION = 1

# Per-piece array leaves spilled verbatim.  ``offsets`` is deliberately
# absent: it is CD-iteration state, overlaid by ``ChunkedBatch.chunk``.
_LEAF_FIELDS = ("values", "col_ids", "labels", "weights", "mask")


def release_free_heap() -> None:
    """Return freed allocator arenas to the OS (glibc ``malloc_trim``).

    The one-chunk-at-a-time spill build allocates and frees each
    chunk's arrays plus the zip writer's buffers in turn; glibc retains
    much of that as arena slack, which then reads as permanent RSS —
    the exact number an out-of-core build exists to bound.  Best-effort
    and Linux-only; a no-op anywhere else."""
    try:
        import ctypes

        ctypes.CDLL("libc.so.6").malloc_trim(0)
    except Exception:  # photon-lint: disable=swallowed-exception (non-glibc platforms: nothing to trim)
        pass


def resolve_spill_dir(spill_dir: str | None) -> str | None:
    """Explicit argument, else ``$PHOTON_ML_TPU_SPILL_DIR``, else None
    (chunks stay host-resident, the pre-round-8 behavior)."""
    if spill_dir is not None:
        return spill_dir
    from photon_ml_tpu.config import read_env

    return read_env("PHOTON_ML_TPU_SPILL_DIR") or None


class ChunkStoreSpillError(RuntimeError):
    """A spill write failed for CAPACITY, not transience: one
    actionable error naming the spill dir, the bytes the chunk needed,
    and the bytes the filesystem had free (ISSUE 9 satellite — the raw
    ``OSError(ENOSPC)`` used to surface from the prefetch thread with
    no context at all)."""

    def __init__(self, spill_dir: str, bytes_needed: int,
                 bytes_free: int | None):
        self.spill_dir = spill_dir
        self.bytes_needed = int(bytes_needed)
        self.bytes_free = bytes_free
        free = ("unknown" if bytes_free is None
                else f"{bytes_free / 1e6:.1f} MB")
        super().__init__(
            f"chunk spill to {spill_dir!r} out of space: chunk needs "
            f"~{bytes_needed / 1e6:.1f} MB, {free} free — free disk "
            "space, point spill_dir/$PHOTON_ML_TPU_SPILL_DIR at a "
            "larger volume, or raise chunk granularity "
            "(chunk_rows / re_chunk_entities) to shrink per-chunk "
            "spill size")


def _free_bytes(path: str) -> int | None:
    """Free bytes on the filesystem holding ``path`` (nearest existing
    ancestor), or None when even that cannot be determined."""
    p = os.path.abspath(path)
    while p and not os.path.exists(p):
        parent = os.path.dirname(p)
        if parent == p:
            break
        p = parent
    try:
        return shutil.disk_usage(p).free
    except OSError:  # photon-lint: disable=swallowed-exception (free-space probe is advisory; the spill error carries 'unknown')
        return None


# Spill dirs already warned about (degrade-to-resident is announced
# ONCE per dir per process, not once per chunk build).
_DEGRADED_DIRS: set[str] = set()
_DEGRADED_LOCK = threading.Lock()


def probe_spill_dir(spill_dir: str | None) -> str | None:
    """``spill_dir`` if it is writable, else None — the documented
    degradation for an unwritable spill dir: the caller falls back to
    the resident (pre-round-8) path with ONE warning instead of dying
    chunks deep into a build.  Streamed random effects, where the
    store is the architecture rather than an optimization, must NOT
    degrade — they keep calling the store directly and surface the
    error."""
    if spill_dir is None:
        return None
    # Unique probe name: spill dirs are SHARED across runs by design
    # (content-addressed warm reuse), so a fixed name would let two
    # concurrent probes race on the remove and spuriously degrade a
    # healthy dir (review finding).
    probe = os.path.join(spill_dir, "chunks",
                         f".probe-{os.getpid()}-{threading.get_ident()}")
    try:
        os.makedirs(os.path.dirname(probe), exist_ok=True)
        with open(probe, "w") as f:
            f.write("ok")
        os.remove(probe)
        return spill_dir
    except OSError as e:
        with _DEGRADED_LOCK:
            first = spill_dir not in _DEGRADED_DIRS
            _DEGRADED_DIRS.add(spill_dir)
        if first:
            logger.warning(
                "spill dir %r is not writable (%r); DEGRADING to the "
                "host-resident path — host RSS is no longer bounded by "
                "the chunk window for this build", spill_dir, e)
            telemetry.count("reliability.degraded")
        return None


def store_key(rows, labels: np.ndarray, weights: np.ndarray, dim: int,
              chunk_rows: int, layout: str, n_dev: int,
              row_capacity: int, drop_ell_with_grr: bool = True) -> str:
    """Content fingerprint of everything that shapes the spilled chunk
    payloads (the plan cache's keying discipline: exact inputs × build
    config × format version; offsets excluded — they are external).

    GRR-layout chunks embed COMPILED plans, so the planner/builder
    semantics version is part of their key — a ``PLANNER_VERSION``
    bump orphans old GRR chunk files exactly as it orphans plan-cache
    entries, instead of warm-serving stale plans to new kernel code.
    ``drop_ell_with_grr`` changes the spilled ELL arrays and keys too.
    """
    from photon_ml_tpu.cache.plan_cache import dataset_fingerprint

    cfg_dict = {"chunk_rows": int(chunk_rows), "layout": layout,
                "n_dev": int(n_dev), "k": int(row_capacity)}
    if layout == "grr":
        from photon_ml_tpu.data.grr import PLANNER_VERSION

        cfg_dict["planner"] = PLANNER_VERSION
        cfg_dict["drop_ell"] = bool(drop_ell_with_grr)
    fp = dataset_fingerprint(
        np.asarray(rows.indptr), np.asarray(rows.vals, np.float32), dim,
        extra=(np.asarray(rows.cols), np.asarray(labels, np.float32),
               np.asarray(weights, np.float32)))
    cfg = hashlib.blake2b(
        json.dumps(cfg_dict, sort_keys=True).encode(),
        digest_size=6).hexdigest()
    return f"{fp}-{cfg}"


# ---------------------------------------------------------------------------
# Encode / decode (the plan cache's tree-path-key scheme, one level up:
# a chunk is 1..n_dev SparseBatch pieces, each optionally carrying a
# compiled GRR plan serialized by the plan cache's own node codec).
# ---------------------------------------------------------------------------


def encode_chunk(chunk) -> tuple[dict, dict]:
    """Chunk (SparseBatch | list of per-device SparseBatch) → (manifest,
    arrays) ready for ``atomic_savez``."""
    from photon_ml_tpu.cache.plan_cache import _encode_node

    pieces = chunk if isinstance(chunk, list) else [chunk]
    arrays: dict = {}
    metas = []
    for j, b in enumerate(pieces):
        pfx = f"p{j}."
        for f in _LEAF_FIELDS:
            arrays[pfx + f] = np.asarray(getattr(b, f))
        metas.append({
            "dim": int(b.dim),
            "grr": _encode_node(b.grr, pfx + "g.", arrays),
        })
    meta = {"version": CHUNK_FORMAT_VERSION,
            "mesh": isinstance(chunk, list), "pieces": metas}
    return meta, arrays


def encode_array_chunk(chunk: dict) -> tuple[dict, dict]:
    """Generic flat array-dict chunk → (manifest, arrays): the scoring
    pipeline's chunk payloads (ISSUE 4) are plain name → ndarray maps,
    not SparseBatch pieces — same spill/mmap/LRU machinery, simpler
    codec."""
    arrays = {k: np.asarray(v) for k, v in chunk.items()}
    meta = {"version": CHUNK_FORMAT_VERSION, "kind": "arrays",
            "keys": sorted(arrays)}
    return meta, arrays


def decode_array_chunk(meta: dict, arrays) -> dict:
    """Inverse of ``encode_array_chunk``; memmap views pass through
    (score chunks stay file-backed in the host window)."""
    if meta.get("version") != CHUNK_FORMAT_VERSION:
        raise ValueError(f"chunk format {meta.get('version')!r} != "
                         f"{CHUNK_FORMAT_VERSION}")
    if meta.get("kind") != "arrays":
        raise ValueError(f"chunk kind {meta.get('kind')!r} != 'arrays'")
    return {k: arrays[k] for k in meta["keys"]}


# Entity-block chunk leaves (streamed random effects, ISSUE 5): a chunk
# is ``re_chunk_entities`` padded entity problems of one size bucket —
# x [C, cap, p] plus [C, cap] scalar planes.  Offsets are (as ever)
# absent: they are CD-iteration state, scattered in at load time from
# the coordinate's resident per-example maps.
_ENTITY_LEAF_FIELDS = ("x", "labels", "weights", "mask")


def encode_entity_chunk(chunk: dict) -> tuple[dict, dict]:
    """Entity-block chunk (name → ndarray with the ``x``/``labels``/
    ``weights``/``mask`` leaves) → (manifest, arrays).  The random-
    effect streaming codec: same spill/mmap/LRU machinery as the
    training/scoring codecs, keyed leaves so a decode can never bind a
    plane to the wrong role."""
    arrays = {f: np.asarray(chunk[f]) for f in _ENTITY_LEAF_FIELDS}
    meta = {"version": CHUNK_FORMAT_VERSION, "kind": "entity_blocks"}
    return meta, arrays


def decode_entity_chunk(meta: dict, arrays) -> dict:
    """Inverse of ``encode_entity_chunk``; memmap views pass through
    (entity blocks stay file-backed in the host window)."""
    if meta.get("version") != CHUNK_FORMAT_VERSION:
        raise ValueError(f"chunk format {meta.get('version')!r} != "
                         f"{CHUNK_FORMAT_VERSION}")
    if meta.get("kind") != "entity_blocks":
        raise ValueError(
            f"chunk kind {meta.get('kind')!r} != 'entity_blocks'")
    return {f: arrays[f] for f in _ENTITY_LEAF_FIELDS}


ENTITY_CHUNK_CODEC = (encode_entity_chunk, decode_entity_chunk)


# Fused-cycle sidecar chunks (ISSUE 11): the cycle-aligned layout
# co-locates, per EXAMPLE chunk, every random effect's per-row entity
# index + (projected) feature planes next to the fixed-effect chunk the
# same rows live in — so ONE prefetched chunk pair feeds every
# coordinate of a fused CD cycle.  Payloads are flat name → ndarray
# maps ("<coordinate>.x" [R, p], "<coordinate>.idx" [R]); the kind tag
# keeps a fused sidecar from ever decoding as a scoring chunk.


def encode_fused_chunk(chunk: dict) -> tuple[dict, dict]:
    """Fused-training sidecar chunk → (manifest, arrays)."""
    arrays = {k: np.asarray(v) for k, v in chunk.items()}
    meta = {"version": CHUNK_FORMAT_VERSION, "kind": "fused_rows",
            "keys": sorted(arrays)}
    return meta, arrays


def decode_fused_chunk(meta: dict, arrays) -> dict:
    """Inverse of ``encode_fused_chunk``; memmap views pass through."""
    if meta.get("version") != CHUNK_FORMAT_VERSION:
        raise ValueError(f"chunk format {meta.get('version')!r} != "
                         f"{CHUNK_FORMAT_VERSION}")
    if meta.get("kind") != "fused_rows":
        raise ValueError(f"chunk kind {meta.get('kind')!r} != "
                         "'fused_rows'")
    return {k: arrays[k] for k in meta["keys"]}


FUSED_CHUNK_CODEC = (encode_fused_chunk, decode_fused_chunk)


def array_content_key(arrays, cfg: dict) -> str:
    """Content fingerprint for chunk payloads derived from plain host
    arrays (the streamed-RE analog of ``store_key``): exact input
    bytes × build configuration; the format version rides in the file
    name as everywhere else.  ``arrays`` is an iterable of ndarrays
    hashed with dtype/shape framing so transposed or reshaped inputs
    cannot collide."""
    h = hashlib.blake2b(digest_size=10)
    for a in arrays:
        a = np.ascontiguousarray(a)
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.view(np.uint8).reshape(-1))
    cfg_h = hashlib.blake2b(
        json.dumps(cfg, sort_keys=True).encode(),
        digest_size=6).hexdigest()
    return f"{h.hexdigest()}-{cfg_h}"


def decode_chunk(meta: dict, arrays):
    """Inverse of ``encode_chunk``; ``arrays`` may be lazy (memmap
    views or an open NpzFile).  Offsets come back ZERO — the caller
    (``ChunkedBatch.chunk``) overlays the current window."""
    from photon_ml_tpu.cache.plan_cache import _decode_node
    from photon_ml_tpu.data.batch import SparseBatch

    if meta.get("version") != CHUNK_FORMAT_VERSION:
        raise ValueError(f"chunk format {meta.get('version')!r} != "
                         f"{CHUNK_FORMAT_VERSION}")
    pieces = []
    for j, pm in enumerate(meta["pieces"]):
        pfx = f"p{j}."
        labels = np.asarray(arrays[pfx + "labels"])
        pieces.append(SparseBatch(
            values=arrays[pfx + "values"],
            col_ids=arrays[pfx + "col_ids"],
            labels=labels,
            weights=arrays[pfx + "weights"],
            offsets=np.zeros(labels.shape[0], np.float32),
            mask=arrays[pfx + "mask"],
            dim=int(pm["dim"]),
            grr=_decode_node(pm["grr"], pfx + "g.", arrays),
        ))
    return pieces if meta["mesh"] else pieces[0]


# Parsed member index per (path, mtime_ns, size): a streaming sweep
# re-opens the same files every pass (window misses), and the zip +
# npy header walk is pure re-derivation — the payload offsets cannot
# change without the stat signature changing.
_NPZ_INDEX: dict = {}
_NPZ_INDEX_LOCK = threading.Lock()
_NPZ_INDEX_MAX = 4096


def _npz_index(path: str) -> tuple:
    """[(member name, dtype, shape, payload offset)] for an
    uncompressed ``.npz``, cached by stat signature."""
    st = os.stat(path)
    sig = (path, st.st_mtime_ns, st.st_size)
    with _NPZ_INDEX_LOCK:
        idx = _NPZ_INDEX.get(sig)
    if idx is not None:
        return idx
    members = []
    with open(path, "rb") as fh, zipfile.ZipFile(fh) as zf:
        for info in zf.infolist():
            if info.compress_type != zipfile.ZIP_STORED:
                raise ValueError(f"compressed member {info.filename!r}")
            fh.seek(info.header_offset)
            hdr = fh.read(30)
            if len(hdr) != 30 or hdr[:4] != b"PK\x03\x04":
                raise ValueError("bad zip local header")
            name_len, extra_len = struct.unpack("<HH", hdr[26:30])
            fh.seek(info.header_offset + 30 + name_len + extra_len)
            version = np.lib.format.read_magic(fh)
            if version == (1, 0):
                shape, fortran, dtype = \
                    np.lib.format.read_array_header_1_0(fh)
            elif version == (2, 0):
                shape, fortran, dtype = \
                    np.lib.format.read_array_header_2_0(fh)
            else:
                raise ValueError(f"npy format {version}")
            if fortran or dtype.hasobject:
                raise ValueError("unsupported npy layout")
            name = info.filename
            if name.endswith(".npy"):
                name = name[:-4]
            members.append((name, dtype, shape, fh.tell()))
    idx = tuple(members)
    with _NPZ_INDEX_LOCK:
        if len(_NPZ_INDEX) >= _NPZ_INDEX_MAX:
            _NPZ_INDEX.clear()
        _NPZ_INDEX[sig] = idx
    return idx


def _open_npz_mmap(path: str) -> dict:
    """Memory-mapped views of every member of an uncompressed ``.npz``.

    ``np.savez`` members are ZIP_STORED whole ``.npy`` files, so each
    array's data sits at (local-header offset + header) — parse the
    30-byte local header for the name/extra lengths (the central
    directory's copies can differ), then the npy header, and
    ``np.memmap`` the payload.  Raises on anything unexpected; the
    caller falls back to a plain copying load."""
    return {name: np.memmap(path, mode="r", dtype=dtype, shape=shape,
                            offset=offset)
            for name, dtype, shape, offset in _npz_index(path)}


class SharedChunkWindow:
    """One LRU residency budget shared by SEVERAL chunk stores.

    The legacy (per-coordinate) CD cycle streams the fixed-effect store
    and each random effect's entity store in turn; with per-store
    windows each coordinate pins its own ``host_max_resident`` chunks
    for the whole descent, so the cycle's true host footprint is
    (window × streamed coordinates) and the coordinates thrash each
    other's budget expectations (ISSUE 11 satellite).  Registering the
    stores in one group makes ``budget`` the TOTAL decoded-chunk bound
    across all of them: admission evicts the globally least-recently-
    used chunk, whichever store owns it — the active coordinate's sweep
    naturally fills the window, and the previous coordinate's stale
    chunks are the first to go.

    Lock order: the group lock is always taken FIRST, store locks
    second (``admit``/``touch`` are called by stores OUTSIDE their own
    lock); eviction is a reference drop, so a reader holding a chunk
    reference is never invalidated.
    """

    def __init__(self, budget: int):
        self.budget = max(1, int(budget))
        self._lock = threading.RLock()
        # (id(store), chunk index) -> store, in LRU order.
        self._order: OrderedDict = OrderedDict()
        self.evictions = 0

    @property
    def n_resident(self) -> int:
        with self._lock:
            return len(self._order)

    def admit(self, store: "ChunkStore", i: int) -> None:
        with self._lock:
            key = (id(store), i)
            if key in self._order:
                self._order.move_to_end(key)
                return
            while len(self._order) >= self.budget:
                (_, j), victim = self._order.popitem(last=False)
                victim._drop(j)
                self.evictions += 1
            self._order[key] = store

    def touch(self, store: "ChunkStore", i: int) -> None:
        with self._lock:
            key = (id(store), i)
            if key in self._order:
                self._order.move_to_end(key)

    def drop_store(self, store: "ChunkStore") -> None:
        """Forget every entry owned by ``store`` (its window was
        cleared directly, e.g. ``drop_resident``)."""
        with self._lock:
            for key in [k for k, s in self._order.items() if s is store]:
                del self._order[key]


class ChunkStore:
    """Spilled chunks on disk + an LRU window of decoded host chunks.

    ``rebuild(i) -> chunk`` is the lineage fallback: a missing or
    unreadable chunk file is re-derived from the original rows (and
    re-spilled), so disk loss degrades to recompute, never to failure.

    Thread contract: ``get`` is safe from the prefetch thread and the
    main thread; mutation of the window happens under one lock.  The
    instrumentation fields (``loads``/``hits``/``rebuilds``/
    ``peak_resident``/``access_log``) back the LRU-bound and
    determinism tests and the bench's stream section.
    """

    def __init__(self, spill_dir: str, key: str, n_chunks: int,
                 host_max_resident: int = 2, rebuild=None, codec=None,
                 window_group: "SharedChunkWindow | None" = None):
        self.dir = os.path.join(spill_dir, "chunks")
        self.key = key
        self.n_chunks = n_chunks
        self.host_max_resident = max(1, int(host_max_resident))
        self._rebuild = rebuild
        # Shared residency budget across stores (ISSUE 11 satellite):
        # when set, the GROUP owns eviction — this store's window is
        # bounded by the group's total budget, not its own count.
        self._window_group = window_group
        # (encode, decode) pair; default is the SparseBatch chunk codec
        # (training), ``(encode_array_chunk, decode_array_chunk)`` for
        # the scoring pipeline's flat array-dict chunks.
        self._encode, self._decode = codec or (encode_chunk, decode_chunk)
        self._resident: OrderedDict = OrderedDict()
        self._lock = threading.RLock()
        self._readers = 0
        self.loads = 0        # disk loads (misses)
        self.hits = 0         # window hits
        self.rebuilds = 0     # corrupt/missing fallbacks taken
        self.spills = 0       # chunk files written
        self.peak_resident = 0
        self.access_log: list[int] = []   # miss+hit order (determinism)

    # -- paths -------------------------------------------------------------

    def path(self, i: int) -> str:
        return os.path.join(
            self.dir, f"{self.key}-c{i:05d}-v{CHUNK_FORMAT_VERSION}.npz")

    def has(self, i: int) -> bool:
        return os.path.exists(self.path(i))

    # -- window ------------------------------------------------------------

    @property
    def n_resident(self) -> int:
        with self._lock:
            return len(self._resident)

    @property
    def resident_nbytes(self) -> int:
        """Anonymous-host bytes the window pins (memmap leaves count
        zero — their pages are file-backed and reclaimable)."""
        total = 0
        with self._lock:
            chunks = list(self._resident.values())
        for ch in chunks:
            if isinstance(ch, dict):            # array-dict chunks
                leaves = list(ch.values())
            else:
                leaves = [getattr(b, f)
                          for b in (ch if isinstance(ch, list) else [ch])
                          for f in _LEAF_FIELDS]
            for a in leaves:
                if not isinstance(a, np.memmap):
                    total += np.asarray(a).nbytes
        return total

    def _admit(self, i: int, chunk) -> None:
        if self._window_group is not None:
            # Group-governed residency: install locally, then let the
            # group evict the global LRU (possibly from another store).
            # The group call happens OUTSIDE this store's lock — lock
            # order is group first, store second, everywhere.
            with self._lock:
                self._resident[i] = chunk
                self._resident.move_to_end(i)
                self.peak_resident = max(self.peak_resident,
                                         len(self._resident))
            self._window_group.admit(self, i)
            return
        with self._lock:
            if i in self._resident:
                self._resident.move_to_end(i)
                return
            while len(self._resident) >= self.host_max_resident:
                self._resident.popitem(last=False)   # LRU; refs freed
            self._resident[i] = chunk
            self.peak_resident = max(self.peak_resident,
                                     len(self._resident))

    def _drop(self, i: int) -> None:
        """Group-eviction callback: forget chunk ``i`` (ref drop)."""
        with self._lock:
            self._resident.pop(i, None)

    def join_window_group(self, group: "SharedChunkWindow | None") -> None:
        """Install (or clear) a shared residency group on a live store.

        Chunks already resident are registered with the group in their
        current LRU order (possibly evicting under the group's budget),
        so a store built before the group existed — the fixed-effect
        chunked batch comes out of dataset prep, streamed-RE stores out
        of the coordinate builders — joins with consistent accounting.
        """
        old = self._window_group
        if old is not None and old is not group:
            old.drop_store(self)
        self._window_group = group
        if group is None:
            return
        with self._lock:
            resident = list(self._resident)
        for i in resident:
            group.admit(self, i)

    def drop_resident(self) -> None:
        """Free the whole window (requires quiescence — see
        ``assert_quiesced``)."""
        self.assert_quiesced()
        with self._lock:
            self._resident.clear()
        if self._window_group is not None:
            self._window_group.drop_store(self)

    # -- reader accounting (prefetch quiescence) ---------------------------

    def begin_read(self) -> None:
        with self._lock:
            self._readers += 1

    def end_read(self) -> None:
        with self._lock:
            self._readers -= 1

    def assert_quiesced(self) -> None:
        """Raise if a prefetch reader is still active — freeing or
        invalidating chunks under a live reader is the use-after-evict
        race this store exists to prevent."""
        with self._lock:
            if self._readers:
                raise RuntimeError(
                    f"chunk store has {self._readers} active prefetch "
                    "reader(s); quiesce the pipeline before freeing "
                    "chunks")

    # -- spill / load ------------------------------------------------------

    def put(self, i: int, chunk, keep_resident: bool | None = None) -> None:
        """Spill chunk ``i`` (atomic write) and optionally admit it to
        the window.  Default admission: only the first
        ``host_max_resident`` chunks — the ones the deterministic sweep
        order will want first."""
        from photon_ml_tpu.cache.plan_cache import atomic_savez

        meta, arrays = self._encode(chunk)
        path = self.path(i)

        def _write():
            # The fault seam sits INSIDE the attempt so a transient
            # injected write error exercises the same retry the real
            # failure would.
            _faults.fire("store.spill", path=path, chunk=i)
            atomic_savez(path, meta, arrays)

        try:
            _retry.run_with_retries(_write, f"chunk spill {path}")
        except OSError as e:
            if e.errno == errno.ENOSPC:
                # Capacity, not transience: ONE actionable error with
                # the numbers the operator needs (satellite — the raw
                # ENOSPC used to propagate from the prefetch thread).
                telemetry.count("reliability.actionable_errors")
                raise ChunkStoreSpillError(
                    os.path.dirname(self.dir) or self.dir,
                    sum(int(np.asarray(a).nbytes)
                        for a in arrays.values()),
                    _free_bytes(self.dir)) from e
            raise
        with self._lock:
            # ``put`` runs on the build thread AND (rebuild re-spill)
            # the prefetch thread — the counter is shared state.
            self.spills += 1
        telemetry.count("store.spills")
        try:
            telemetry.count("store.bytes_spilled",
                            os.path.getsize(self.path(i)))
        except OSError:  # photon-lint: disable=swallowed-exception (racing cleanup; the size metric is best-effort)
            pass
        if keep_resident is None:
            keep_resident = i < self.host_max_resident
        if keep_resident:
            self._admit(i, chunk)

    def get(self, i: int):
        """Chunk ``i`` as host pieces: window hit, else disk load
        (memory-mapped), else rebuild-from-lineage + re-spill."""
        with self._lock:
            if i in self._resident:
                self._resident.move_to_end(i)
                self.hits += 1
                self.access_log.append(i)
                hit = self._resident[i]
                telemetry.count("store.hits")
            else:
                hit = None
        if hit is not None:
            if self._window_group is not None:
                self._window_group.touch(self, i)
            return hit
        chunk = self._load(i)
        self._admit(i, chunk)
        return chunk

    def _load(self, i: int):
        path = self.path(i)
        with self._lock:
            self.access_log.append(i)
            self.loads += 1
        telemetry.count("store.loads")

        def _attempt():
            # Fault seam per ATTEMPT (a transient injected read error
            # exercises the same bounded retry a flaky disk would).
            _faults.fire("store.load", path=path, chunk=i)
            try:
                arrays = _open_npz_mmap(path)
                telemetry.count("store.mmap_loads")
            except (zipfile.BadZipFile, ValueError, OSError):
                # mmap parse surprise: fall back to a copying load
                # before declaring the file dead.
                arrays = dict(np.load(path, allow_pickle=False))
                telemetry.count("store.copy_loads")
            try:
                telemetry.count("store.bytes_read",
                                os.path.getsize(path))
            except OSError:  # photon-lint: disable=swallowed-exception (best-effort size metric; racing cleanup)
                pass
            meta = json.loads(bytes(np.asarray(arrays["__meta__"]))
                              .decode())
            return self._decode(meta, arrays)

        try:
            # Transient read errors (EIO and friends) retry with
            # bounded backoff before the lineage rebuild; corruption
            # (ValueError / BadZipFile) and ENOENT go straight to
            # rebuild — retrying cannot change file content.
            return _retry.run_with_retries(
                _attempt, f"chunk load {path}")
        except Exception as e:
            if self._rebuild is None:
                raise
            logger.warning(
                "chunk store: unreadable chunk %s (%r); rebuilding",
                path, e)
            with self._lock:
                self.rebuilds += 1
            telemetry.count("store.rebuilds")
            chunk = self._rebuild(i)
            try:
                self.put(i, chunk, keep_resident=False)
            except Exception as we:   # re-spill is best-effort
                logger.warning("chunk store: re-spill of chunk %d "
                               "failed (%r)", i, we)
            return chunk
