"""Feature normalization applied in-kernel (no data rewrite).

Reference counterpart: ``NormalizationContext`` / ``NormalizationType``
(photon-api ``com.linkedin.photon.ml.normalization`` [expected path, mount
unavailable — see SURVEY.md]).

The reference's key design choice — normalize *inside the aggregators*
rather than rewriting the dataset — carries over directly and is even more
valuable on TPU: the HBM-resident batch stays untouched (and sparse), while
the transform is algebra on the [dim]-sized model vector:

    x' = (x − shift) ⊙ factor
    margin'  = Σ_j x_j·(f_j·w_j) − Σ_j s_j·f_j·w_j
             = margin(x, f ⊙ w) − dot(s ⊙ f, w)

So a normalized objective evaluates the *raw* batch at the scaled
coefficients ``f ⊙ w`` and subtracts a scalar shift-correction — two O(dim)
ops, zero extra HBM traffic, sparsity preserved (shift never touches the
[n,k] values).  Gradients get the chain rule applied on the way out.

Types mirror the reference enum: NONE, SCALE_WITH_STANDARD_DEVIATION,
SCALE_WITH_MAX_MAGNITUDE, STANDARDIZATION.
"""

from __future__ import annotations

import enum

import jax
import jax.numpy as jnp
from flax import struct

Array = jax.Array


class NormalizationType(str, enum.Enum):
    NONE = "NONE"
    SCALE_WITH_STANDARD_DEVIATION = "SCALE_WITH_STANDARD_DEVIATION"
    SCALE_WITH_MAX_MAGNITUDE = "SCALE_WITH_MAX_MAGNITUDE"
    STANDARDIZATION = "STANDARDIZATION"


@struct.dataclass
class NormalizationContext:
    """factors/shifts over the feature space; identity when both are None."""

    factors: Array | None = None  # [dim] or None (≡ ones)
    shifts: Array | None = None   # [dim] or None (≡ zeros)

    @staticmethod
    def identity() -> "NormalizationContext":
        return NormalizationContext(factors=None, shifts=None)

    @property
    def is_identity(self) -> bool:
        return self.factors is None and self.shifts is None

    # The three hooks the objective uses -----------------------------------

    def model_to_raw(self, w: Array) -> Array:
        """Coefficients in normalized space → the vector to dot raw x with."""
        return w if self.factors is None else w * self.factors

    def raw_to_model(self, w_raw: Array) -> Array:
        """Inverse of ``model_to_raw`` (warm-starting from a saved
        raw-space model; the intercept's margin-correction fold is
        undone by the caller, which knows the intercept index)."""
        return w_raw if self.factors is None else w_raw / self.factors

    def margin_correction(self, w: Array) -> Array:
        """Scalar subtracted from every margin: dot(shifts ⊙ factors, w)."""
        if self.shifts is None:
            return jnp.asarray(0.0, w.dtype)
        f = self.factors if self.factors is not None else jnp.ones_like(w)
        return jnp.vdot(self.shifts * f, w)

    def grad_to_model(self, g_raw: Array, r_sum: Array) -> Array:
        """Chain rule: ∂margin/∂w_j = f_j·(x_j − s_j) ⇒
        g_model = f ⊙ g_raw − (Σ_i r_i)·(f ⊙ s).

        ``g_raw`` is X^T r on raw data; ``r_sum`` is Σ r_i (masked+weighted).
        """
        if self.factors is None and self.shifts is None:
            return g_raw
        f = self.factors if self.factors is not None else jnp.ones_like(g_raw)
        g = g_raw * f
        if self.shifts is not None:
            g = g - r_sum * (f * self.shifts)
        return g


def compute_normalization(
    stats_mean: Array,
    stats_std: Array,
    stats_max_abs: Array,
    norm_type: NormalizationType,
    intercept_index: int | None = None,
) -> NormalizationContext:
    """Build a context from feature summary statistics.

    Mirrors the reference factory (NormalizationContext.apply over a
    ``BasicStatisticalSummary``): std-scaling uses 1/σ (σ==0 → factor 1),
    max-magnitude uses 1/max|x|, standardization additionally shifts by the
    mean.  The intercept coordinate is never scaled or shifted.
    """
    if norm_type == NormalizationType.NONE:
        return NormalizationContext.identity()

    safe = lambda a: jnp.where(a > 0.0, a, 1.0)
    if norm_type == NormalizationType.SCALE_WITH_STANDARD_DEVIATION:
        factors, shifts = 1.0 / safe(stats_std), None
    elif norm_type == NormalizationType.SCALE_WITH_MAX_MAGNITUDE:
        factors, shifts = 1.0 / safe(stats_max_abs), None
    elif norm_type == NormalizationType.STANDARDIZATION:
        factors, shifts = 1.0 / safe(stats_std), stats_mean
    else:
        raise ValueError(f"Unknown normalization type {norm_type}")

    if intercept_index is not None:
        factors = factors.at[intercept_index].set(1.0)
        if shifts is not None:
            shifts = shifts.at[intercept_index].set(0.0)
    return NormalizationContext(factors=factors, shifts=shifts)
