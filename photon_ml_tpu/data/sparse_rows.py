"""Array-backed sparse example rows: the scale-class ETL container.

Reference counterpart: the reference's per-example sparse Breeze vectors
inside ``RDD[LabeledPoint]`` / ``RDD[GameDatum]`` (photon-api
``com.linkedin.photon.ml.data`` [expected paths, mount unavailable — see
SURVEY.md §2.4]).  The reference can afford one JVM object per example
because Spark streams them; a host ETL that feeds a TPU cannot — at the
KDD2012 scale (10⁸ examples) a ``list[tuple[np.ndarray, np.ndarray]]``
is tens of GB of Python object headers and every pass over it is a
Python-speed loop.

``SparseRows`` is the CSR answer: three flat arrays (``indptr``,
``cols``, ``vals``) hold every example, so memory is exactly
nnz·8B + (n+1)·8B and every ETL transformation — canonicalization,
row subsetting, intercept append, ELL densification — is a vectorized
numpy pass.  It quacks like the legacy row list (``len``, indexing,
slicing, iteration yield ``(col_ids, values)`` views) so existing
consumers keep working, while hot paths (``make_sparse_batch``,
``shard_sparse_batch``, entity grouping, projection) detect it and take
the flat-array fast path.

Rows are kept CANONICAL: within each row, ``cols`` strictly increasing
(sorted, duplicates summed).  ``from_flat`` enforces this once,
vectorized; everything downstream relies on it (``SparseBatch`` requires
unique per-row ids for its Hessian diagonal).
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class SparseRows:
    """CSR-layout sparse rows: example i owns ``cols/vals[indptr[i]:indptr[i+1]]``."""

    indptr: np.ndarray  # int64 [n+1], monotone, indptr[0] == 0
    cols: np.ndarray    # int32 [nnz], strictly increasing within each row
    vals: np.ndarray    # float32 [nnz]

    # -- construction -------------------------------------------------------

    @staticmethod
    def from_rows(rows) -> "SparseRows":
        """From a legacy ``list[(col_ids, values)]`` (or any iterable of
        pairs).  Canonicalizes."""
        if isinstance(rows, SparseRows):
            return rows
        counts = np.fromiter((len(c) for c, _ in rows), np.int64,
                             count=len(rows))
        indptr = np.zeros(len(rows) + 1, np.int64)
        np.cumsum(counts, out=indptr[1:])
        nnz = int(indptr[-1])
        cols = np.empty(nnz, np.int64)
        vals = np.empty(nnz, np.float64)
        at = 0
        for c, v in rows:
            cols[at:at + len(c)] = c
            vals[at:at + len(c)] = v
            at += len(c)
        return SparseRows.from_flat(indptr, cols, vals)

    @staticmethod
    def from_flat(indptr: np.ndarray, cols: np.ndarray, vals: np.ndarray,
                  clip_dim: int | None = None) -> "SparseRows":
        """From raw CSR arrays (e.g. the native LIBSVM parser's output):
        one vectorized pass sorts each row by column id, sums duplicate
        ids, and (optionally) drops entries with ``col >= clip_dim``.

        ``cols`` may arrive in any order and with repeats; negative ids
        raise (they indicate an upstream indexing bug)."""
        indptr = np.asarray(indptr, np.int64)
        n = len(indptr) - 1
        cols = np.asarray(cols)
        vals = np.asarray(vals)
        if cols.size and int(cols.min()) < 0:
            raise ValueError("negative column id in sparse rows")
        row_of = np.repeat(np.arange(n, dtype=np.int64), np.diff(indptr))
        if clip_dim is not None:
            keep = cols < clip_dim
            if not bool(keep.all()):
                cols, vals, row_of = cols[keep], vals[keep], row_of[keep]
        # Already-canonical fast path: most parsers emit rows sorted and
        # unique (LIBSVM convention), and the O(nnz) check is ~50×
        # cheaper than the O(nnz log nnz) lexsort it skips — at 10⁸ nnz
        # the sort is minutes, the check is a second.
        if len(cols) == 0 or bool(
            ((cols[1:] > cols[:-1]) | (row_of[1:] != row_of[:-1])).all()
        ):
            counts0 = np.bincount(row_of, minlength=n)
            out_indptr0 = np.zeros(n + 1, np.int64)
            np.cumsum(counts0, out=out_indptr0[1:])
            return SparseRows(
                indptr=out_indptr0,
                cols=np.ascontiguousarray(cols, np.int32),
                vals=np.ascontiguousarray(vals, np.float32),
            )
        # Sort by (row, col); detect duplicate (row, col) groups; sum
        # each group with one reduceat.
        order = np.lexsort((cols, row_of))
        cols_s = cols[order]
        vals_s = vals[order]
        row_s = row_of[order]
        if len(cols_s):
            new_group = np.empty(len(cols_s), bool)
            new_group[0] = True
            np.logical_or(row_s[1:] != row_s[:-1], cols_s[1:] != cols_s[:-1],
                          out=new_group[1:])
            starts = np.flatnonzero(new_group)
            g_cols = cols_s[starts]
            g_rows = row_s[starts]
            g_vals = np.add.reduceat(vals_s.astype(np.float64), starts)
            counts = np.bincount(g_rows, minlength=n)
        else:
            g_cols = cols_s
            g_rows = row_s
            g_vals = vals_s
            counts = np.zeros(n, np.int64)
        out_indptr = np.zeros(n + 1, np.int64)
        np.cumsum(counts, out=out_indptr[1:])
        return SparseRows(
            indptr=out_indptr,
            cols=np.ascontiguousarray(g_cols, np.int32),
            vals=np.ascontiguousarray(g_vals, np.float32),
        )

    @staticmethod
    def concat(parts: list["SparseRows"]) -> "SparseRows":
        """Row-wise concatenation (chunked readers assemble with this)."""
        if not parts:
            return SparseRows(np.zeros(1, np.int64),
                              np.zeros(0, np.int32), np.zeros(0, np.float32))
        indptrs = [np.zeros(1, np.int64)]
        base = 0
        for p in parts:  # robust to zero-row parts (empty indptr[1:])
            indptrs.append(p.indptr[1:] + base)
            base += p.nnz
        return SparseRows(
            indptr=np.concatenate(indptrs),
            cols=np.concatenate([p.cols for p in parts]),
            vals=np.concatenate([p.vals for p in parts]),
        )

    # -- shape / stats ------------------------------------------------------

    def __len__(self) -> int:
        return len(self.indptr) - 1

    @property
    def nnz(self) -> int:
        return int(self.indptr[-1])

    def counts(self) -> np.ndarray:
        """Per-row nnz [n]."""
        return np.diff(self.indptr)

    @property
    def max_nnz(self) -> int:
        return int(self.counts().max()) if len(self) else 0

    @property
    def max_col(self) -> int:
        return int(self.cols.max()) if self.nnz else -1

    def row_of(self) -> np.ndarray:
        """Row index of each stored entry [nnz]."""
        return np.repeat(np.arange(len(self), dtype=np.int64), self.counts())

    # -- legacy row-list protocol ------------------------------------------

    def __getitem__(self, i):
        if isinstance(i, slice):
            start, stop, step = i.indices(len(self))
            if step != 1:
                return self.take(np.arange(start, stop, step))
            lo, hi = self.indptr[start], self.indptr[stop]
            return SparseRows(
                indptr=self.indptr[start:stop + 1] - lo,
                cols=self.cols[lo:hi], vals=self.vals[lo:hi],
            )
        if isinstance(i, (np.ndarray, list)):
            return self.take(np.asarray(i))
        i = int(i)
        if i < 0:
            i += len(self)
        if not 0 <= i < len(self):
            raise IndexError(f"row {i} out of range for {len(self)} rows")
        lo, hi = self.indptr[i], self.indptr[i + 1]
        return self.cols[lo:hi], self.vals[lo:hi]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # -- vectorized transforms ---------------------------------------------

    def take(self, idx: np.ndarray) -> "SparseRows":
        """Row subset/reorder (train/validation splits, shard slicing) —
        vectorized; no per-row Python."""
        idx = np.asarray(idx, np.int64)
        counts = self.counts()[idx]
        out_indptr = np.zeros(len(idx) + 1, np.int64)
        np.cumsum(counts, out=out_indptr[1:])
        # Source position of each output entry: for output row j at
        # offset t, src = indptr[idx[j]] + t.
        row_of_out = np.repeat(np.arange(len(idx), dtype=np.int64), counts)
        within = np.arange(int(out_indptr[-1]), dtype=np.int64) \
            - out_indptr[row_of_out]
        src = self.indptr[idx[row_of_out]] + within
        return SparseRows(indptr=out_indptr, cols=self.cols[src],
                          vals=self.vals[src])

    def with_constant_col(self, col: int, value: float = 1.0) -> "SparseRows":
        """Append one column (id ``col``, same ``value``) to every row —
        the intercept transform.  ``col`` must exceed every stored id
        (canonical order is preserved by appending at row ends)."""
        if self.nnz and col <= self.max_col:
            raise ValueError(
                f"intercept column {col} must be > max col {self.max_col}")
        n = len(self)
        out_indptr = self.indptr + np.arange(n + 1, dtype=np.int64)
        nnz_out = int(out_indptr[-1])
        # Each row's new entry sits at its (exclusive) end; everything
        # else copies over in order.  Two boolean-scatter passes total —
        # O(nnz) with small constants (this runs on 10⁸-entry inputs).
        cols = np.empty(nnz_out, np.int32)
        vals = np.empty(nnz_out, np.float32)
        keep = np.ones(nnz_out, bool)
        keep[out_indptr[1:] - 1] = False
        cols[~keep] = col
        vals[~keep] = value
        cols[keep] = self.cols
        vals[keep] = self.vals
        return SparseRows(indptr=out_indptr, cols=cols, vals=vals)

    def to_ell(self, row_capacity: int | None = None,
               pad_to: int | None = None
               ) -> tuple[np.ndarray, np.ndarray]:
        """Densify to the padded-ELL pair ``(col_ids [n_out, k],
        values [n_out, k])`` in one vectorized scatter.  Padding entries
        are (col 0, value 0.0) per the SparseBatch convention."""
        n = len(self)
        k = row_capacity if row_capacity is not None else max(self.max_nnz, 1)
        if self.max_nnz > k:
            bad = int(np.argmax(self.counts() > k))
            raise ValueError(
                f"row {bad} nnz {int(self.counts()[bad])} exceeds "
                f"capacity {k}")
        n_out = max(pad_to or n, n)
        cols2d = np.zeros((n_out, max(k, 1)), np.int32)
        vals2d = np.zeros((n_out, max(k, 1)), np.float32)
        row = self.row_of()
        pos = np.arange(self.nnz, dtype=np.int64) - self.indptr[row]
        cols2d[row, pos] = self.cols
        vals2d[row, pos] = self.vals
        return cols2d, vals2d

    def dot_dense(self, w: np.ndarray) -> np.ndarray:
        """Host-side X·w [n] (transformer scoring path) — one segment
        reduction instead of a per-row Python loop."""
        contrib = self.vals.astype(np.float64) * w[self.cols]
        # Row sums via prefix-sum differences — exact for empty rows,
        # no scatter.
        cs = np.zeros(self.nnz + 1, np.float64)
        np.cumsum(contrib, out=cs[1:])
        return (cs[self.indptr[1:]] - cs[self.indptr[:-1]]).astype(np.float32)

    def to_dense(self, dim: int) -> np.ndarray:
        """Densify to [n, dim] float32 (small shards only)."""
        x = np.zeros((len(self), dim), np.float32)
        x[self.row_of(), self.cols] = self.vals
        return x
