"""GRR (gather-route-reduce) layout: the TPU-fast sparse contraction plan.

THE perf-critical design of this framework.  Both directions of the
sparse GLM hot loop are instances of ``out[s] = Σ_e val_e·table[idx_e]``
(margins: s=example, table=w; gradient: s=feature, table=residual), and
XLA lowers both the gather and the scatter form to *scalar* loops on TPU
(~1 GB/s measured on v5e).  The TensorCore's only fast irregular-data
primitive is the within-register lane gather (``tpu.DynamicGather``, via
``take_along_axis`` on equal [128,128] shapes).  This module compiles
the sparse matrix — once, on the host, like the reference's one-time
``partitionBy`` shuffle (SURVEY.md §5.8 [mount unavailable]) — into a
static plan that expresses the whole contraction in exactly that
primitive:

- Nonzeros are **2-D blocked** into supertiles of 16384 slots, one per
  (segment-window × table-window) pair: the table window (16384 entries
  = a [128,128] VMEM tile) bounds what the supertile gathers; the
  segment window (16384/CAP segments) bounds what it reduces into.
- Within a supertile, each element *starts* in the sublane matching its
  table index's window sub-tile ((idx mod WIN) // 128), with the gather
  plane carrying its lane residue (idx mod 128) — making the gather ONE
  lane-gather straight from the *untransposed* window (row s of the
  [128,128] window IS table[gw·WIN + 128s ...]) — and *ends* at its
  segment's reduction slot, reached by an arbitrary-but-static
  permutation realized as a 3-stage Clos route (``ops.crossbar``;
  switches from König edge-coloring, computed here, applied by
  ``ops.grr_kernel``).
- Each segment owns CAP slots per table-window (capacity planes are
  contiguous 16-row blocks, so the reduction is CAP static-slice adds);
  per-(segment, window) overflow beyond CAP — and per-residue overflow
  beyond 128 starts — goes to a small COO **spill** list handled by the
  XLA path.
- **Hot columns** (denser than ~1/16) would overflow every capacity;
  they are split out into a dense [n, H] side matrix and handled on the
  MXU (``GrrPair``), which is also where an intercept column naturally
  lands.

The plan is static per dataset: every optimizer iteration replays it
with new table values, paying ~7 bytes of HBM traffic per slot and ~6
vector ops per 16384 slots — measured ~7 Gslot/s on v5e vs ~0.06 for
the XLA scatter, a ~100× speedup of the framework's hot loop.
"""

from __future__ import annotations

import logging
import time

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

Array = jax.Array

logger = logging.getLogger(__name__)

WIN = 16384          # table entries per gather window ([128,128] VMEM tile)
TILE = 128
SLOTS = TILE * TILE  # nonzero slots per supertile

# Planner/builder semantics version: part of every plan-cache key
# (photon_ml_tpu.cache.plan_cache), so cached plans from an older
# planner are clean misses.  Bump on ANY change that alters the plan a
# given (cols, vals, dim, options) input compiles to — capacity
# heuristics, range planning, routing, overflow economics.
PLANNER_VERSION = 1

# Default on-disk plan cache location (build_grr_pair /
# build_sharded_grr_pairs ``cache_dir=None`` resolves through this).
PLAN_CACHE_ENV = "PHOTON_ML_TPU_PLAN_CACHE"


def _resolve_cache_dir(cache_dir: "str | None") -> "str | None":
    from photon_ml_tpu.config import read_env

    return cache_dir or read_env(PLAN_CACHE_ENV) or None


class _SpillWarnings:
    """Rate-limited "GRR spill fraction" reporting.

    A sharded/chunked plan build runs one direction build per (shard ×
    direction × range part) — the per-build warning printed ~20
    identical lines per dryrun (round-5 verdict: the spam buries real
    signal).  Inside a collecting scope (entered by ``build_grr_pair``
    and ``build_sharded_grr_pairs``; re-entrant, thread-safe — the
    direction builds run in a thread pool) the per-build lines are
    aggregated into ONE count/min/max/mean summary at scope exit.

    Direction builds OUTSIDE any scope (the raw builder API — ISSUE 16
    satellite: these used to print one raw line per call) aggregate
    the same way into a time-windowed summary: the first flagged build
    reports immediately, then further flagged builds buffer for
    ``_UNSCOPED_WINDOW_S`` and the next note past the window emits ONE
    summary for the whole burst.  Every emission also feeds the
    ``grr.spill_flagged_builds`` telemetry counter so the report/bench
    tiers see the signal without parsing log text."""

    _THRESHOLD = 0.05    # COO fraction below which no one needs to act
    _UNSCOPED_WINDOW_S = 30.0   # unscoped-burst dedupe window

    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self._depth = 0
        self._builds = 0
        self._flagged: list = []   # fractions over threshold
        self._last_emit: float | None = None

    def __enter__(self):
        with self._lock:
            if self._depth == 0:
                # Flush (or, when nothing was flagged, discard) any
                # buffered unscoped builds first, so the scope's own
                # summary counts only its builds.
                builds, flagged = self._drain()
            else:
                builds = flagged = None
            self._depth += 1
        if flagged:
            self._emit(builds, flagged)
        return self

    def __exit__(self, *exc):
        with self._lock:
            self._depth -= 1
            if self._depth:
                return False
            builds, flagged = self._drain()
        if flagged:
            self._emit(builds, flagged)
        return False

    def _drain(self) -> tuple[int, list]:
        """Take + reset the buffered stats (caller holds the lock)."""
        builds, flagged = self._builds, self._flagged
        self._builds, self._flagged = 0, []
        return builds, flagged

    def _emit(self, builds: int, flagged: list) -> None:
        from photon_ml_tpu import telemetry

        telemetry.count("grr.spill_flagged_builds", len(flagged))
        logger.warning(
            "GRR spill fraction >%.0f%% on the XLA fallback in %d "
            "of %d direction builds (min %.1f%%, max %.1f%%, mean "
            "%.1f%%) — consider a larger cap or a lower hot-column "
            "threshold",
            100 * self._THRESHOLD, len(flagged), builds,
            100 * min(flagged), 100 * max(flagged),
            100 * sum(flagged) / len(flagged))

    def note(self, m_coo: int, total: int) -> None:
        if not total:
            return
        frac = m_coo / total
        with self._lock:
            self._builds += 1
            if frac > self._THRESHOLD:
                self._flagged.append(frac)
            if self._depth:
                return
            if not self._flagged:
                return
            now = time.monotonic()
            if (self._last_emit is not None
                    and now - self._last_emit < self._UNSCOPED_WINDOW_S):
                return               # buffer the burst
            self._last_emit = now
            builds, flagged = self._drain()
        self._emit(builds, flagged)


_spill_warnings = _SpillWarnings()


def _collect_spill_warnings(fn):
    """Aggregate per-direction spill warnings over one plan build."""
    import functools

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with _spill_warnings:
            return fn(*args, **kwargs)

    return wrapped


def collect_spill_warnings():
    """Public aggregation scope for MULTI-build operations (ISSUE 4
    satellite): a sharded/chunked build that compiles several plan
    families — ``build_chunked_batch``'s per-chunk builds and rebuild
    healing, ``shard_sparse_batch``'s per-shard set — enters this once
    and every nested ``build_grr_pair``/``build_sharded_grr_pairs``
    scope folds into ONE summary at the outermost exit (the scope is
    re-entrant), instead of one line per sub-plan (the MULTICHIP_r05
    tail printed 15+)."""
    return _spill_warnings


def _next_pow2(x: int) -> int:
    return 1 << max(0, int(x - 1).bit_length())


def _group_ranks(keys: np.ndarray) -> np.ndarray:
    """Rank of each entry within its key group (0-based; assignment of
    ranks within a group is arbitrary — callers only need distinctness).

    Build-time hot path at 10⁸ entries, so two scale fast paths:
    already-sorted keys (the row direction's (seg, window) keys arrive
    in ELL row-major order) rank in one O(n) run-length pass with no
    sort at all; otherwise a STABLE argsort — numpy's stable kind is a
    radix sort for integer dtypes, O(n·passes) not O(n log n) — over
    int32-compressed keys when the range allows (halves the passes)."""
    n = keys.size
    if n == 0:
        return np.zeros(0, np.int64)
    if bool((keys[1:] >= keys[:-1]).all()):
        newgrp = np.r_[True, keys[1:] != keys[:-1]]
        gstart = np.maximum.accumulate(
            np.where(newgrp, np.arange(n), 0))
        return np.arange(n) - gstart
    sort_keys = keys
    if keys.dtype.itemsize > 4 and 0 <= int(keys.min()) \
            and int(keys.max()) < np.iinfo(np.int32).max:
        sort_keys = keys.astype(np.int32)
    order = np.argsort(sort_keys, kind="stable")
    sk = keys[order]
    newgrp = np.r_[True, sk[1:] != sk[:-1]]
    gstart = np.maximum.accumulate(np.where(newgrp, np.arange(n), 0))
    ranks = np.empty(n, np.int64)
    ranks[order] = np.arange(n) - gstart
    return ranks


@struct.dataclass
class GrrDirection:
    """One direction's compiled contraction plan (see module docstring)."""

    g1: Array            # [n_st,128,128] i8 — gather ∘ route stage 1
    g2: Array            # [n_st,128,128] i8 — route stage 2 (on transposed)
    g3: Array            # [n_st,128,128] i8 — route stage 3
    vals: Array          # [n_st,128,128] f32 — values in final slot order
    gw_of_st: Array      # [n_st] i32
    ow_of_st: Array      # [n_st] i32
    first_of_ow: Array   # [n_st] i32
    spill_idx: Array     # [m] i32 — overflow COO (XLA fallback path)
    spill_seg: Array     # [m] i32
    spill_val: Array     # [m] f32
    table_len: int = struct.field(pytree_node=False)
    n_segments: int = struct.field(pytree_node=False)
    cap: int = struct.field(pytree_node=False)
    n_gw: int = struct.field(pytree_node=False)
    n_ow: int = struct.field(pytree_node=False)
    # Dense-grid layout (the fast kernel arrangement, chosen when the
    # (gw × ow) block grid is ≥ ~70% occupied — true for all production
    # shapes; level-2 overflow plans are usually sparser and keep the
    # legacy order):  tiles are gw-major over the FULL padded grid
    # (missing blocks = zero dummy tiles), ``gw_of_st`` holds the window
    # id per DENSE_B-tile group (length n_st // DENSE_B), and
    # ``ow_of_st``/``first_of_ow`` are empty — a tile's grid position IS
    # its (gw, ow), so the kernel emits per-tile partials and the ow
    # reduction is a dense axis sum (no revisiting, no scatter);
    # measured ~20% faster per tile than the revisiting kernel on v5e.
    dense_grid: bool = struct.field(pytree_node=False, default=False)
    # Overflow plan chain over the heavy tail: under power-law skew the
    # groups that overflow ``cap`` can dwarf the kernel itself if left
    # to the XLA segment_sum fallback (measured 18 ms of a 23 ms
    # gradient at the bench shapes).  A recursive plan with its own
    # (auto, larger) cap absorbs them at kernel speed; the chain
    # recurses while the residual stays above the overflow threshold
    # and each level passes the slots-per-entry economy bound (sharded
    # plans stay one-deep for mesh-uniform padding).
    overflow: "GrrDirection | None" = None

    @property
    def n_supertiles(self) -> int:
        return self.vals.shape[0]

    @property
    def n_spill(self) -> int:
        return int(self.spill_idx.shape[0])

    @property
    def n_ow_padded(self) -> int:
        """Dense grid: padded ow count (n_supertiles / n_gw)."""
        return self.n_supertiles // self.n_gw

    def contract(self, table: Array) -> Array:
        """``out[s] = Σ val_e · table[idx_e]`` for this plan — [n_segments]."""
        from photon_ml_tpu.config import read_env
        from photon_ml_tpu.ops.grr_kernel import (
            grr_contract_jnp,
            grr_contract_jnp_dense,
            grr_contract_kernel,
            grr_contract_kernel_dense,
        )

        pad = self.n_gw * WIN - self.table_len
        t = jnp.concatenate(
            [table.astype(jnp.float32), jnp.zeros((pad,), jnp.float32)]
        )
        # Window rows ARE table sub-tiles (no transpose: the ETL keys
        # start rows by (idx%WIN)//128 and gathers lanes by idx%128).
        table_t = t.reshape(self.n_gw, TILE, TILE)

        use_kernel = (
            jax.default_backend() == "tpu"
            and read_env("PHOTON_ML_TPU_GRR") != "0"
        )
        if self.dense_grid:
            if use_kernel:
                out2d = grr_contract_kernel_dense(
                    table_t, self.g1, self.g2, self.g3, self.vals,
                    self.gw_of_st, n_ow_p=self.n_ow_padded, cap=self.cap,
                )
            else:
                out2d = grr_contract_jnp_dense(
                    table_t, self.g1, self.g2, self.g3, self.vals,
                    n_ow_p=self.n_ow_padded, cap=self.cap,
                )
        elif use_kernel:
            out2d = grr_contract_kernel(
                table_t, self.g1, self.g2, self.g3, self.vals,
                self.gw_of_st, self.ow_of_st, self.first_of_ow,
                n_ow=self.n_ow, cap=self.cap,
            )
        else:
            out2d = grr_contract_jnp(
                table_t, self.g1, self.g2, self.g3, self.vals,
                self.gw_of_st, self.ow_of_st, n_ow=self.n_ow, cap=self.cap,
            )
        out = out2d.reshape(-1)[: self.n_segments]
        if self.overflow is not None:
            out = out + self.overflow.contract(table)
        if self.n_spill:
            contrib = self.spill_val * table[self.spill_idx]
            out = out + jax.ops.segment_sum(
                contrib, self.spill_seg, num_segments=self.n_segments
            )
        return out

    def squared(self) -> "GrrDirection":
        """Same plan with values squared (Hessian-diagonal aggregation) —
        placement is value-independent, so only the streams change."""
        return self.replace(
            vals=self.vals * self.vals,
            spill_val=self.spill_val * self.spill_val,
            overflow=(None if self.overflow is None
                      else self.overflow.squared()),
        )

    def plan_stats(self) -> dict:
        """Host-side placement accounting (diagnostics/bench): entries
        on the level-1 kernel, per-overflow-level entries, and the COO
        residual that stays on the XLA scatter path."""
        lvl1 = int(np.count_nonzero(np.asarray(self.vals)))
        levels = []
        coo = 0
        d = self
        while d is not None:
            if d is not self:
                levels.append(int(np.count_nonzero(np.asarray(d.vals))))
            coo += int(np.count_nonzero(np.asarray(d.spill_val)))
            d = d.overflow
        total = lvl1 + sum(levels) + coo
        return {
            "entries": total,
            "level1": lvl1,
            "overflow_levels": levels,
            "coo": coo,
            "coo_frac": coo / total if total else 0.0,
            "spill_frac": ((sum(levels) + coo) / total) if total else 0.0,
            "supertiles": self.n_supertiles,
            "cap": self.cap,
            "fill": lvl1 / (self.n_supertiles * SLOTS)
            if self.n_supertiles else 0.0,
        }


@struct.dataclass
class GrrRangeSplit:
    """Column-range split of one contraction direction (the row/margins
    direction under power-law column popularity — PERF.md "known next
    lever", round-4 verdict item #1).

    Skewed column ids concentrate mass in the low table windows (44% of
    entries in window 0 at the KDD shape), so a single global
    slots-per-(segment, window) capacity is wrong everywhere: the mean
    heuristic under-caps the heavy windows (mass spills to overflow
    levels and the COO scatter) and over-caps the tail.  The fix is a
    partition of the table axis into contiguous, window-aligned ranges
    of roughly homogeneous per-(segment, window) occupancy — one
    ``GrrDirection`` sub-plan per range with its OWN capacity:

        out[s] = Σ_r  plan_r.contract(table[lo_r:hi_r])

    Same segment space, so the combine is a dense add of [n_segments]
    partials; the table slices are static, so there is no permutation
    or gather anywhere — only the plan build decides who owns which
    window.  Duck-types the ``GrrDirection`` surface that ``GrrPair``
    and the objectives consume (``contract`` / ``squared`` /
    ``n_segments``).
    """

    parts: tuple          # tuple[GrrDirection, ...] — pytree children
    bounds: tuple = struct.field(pytree_node=False)  # len(parts)+1 col ids
    table_len: int = struct.field(pytree_node=False)
    n_segments: int = struct.field(pytree_node=False)

    @property
    def n_spill(self) -> int:
        return sum(p.n_spill for p in self.parts)

    def contract(self, table: Array) -> Array:
        out = None
        for p, lo, hi in zip(self.parts, self.bounds[:-1], self.bounds[1:]):
            part = p.contract(table[lo:hi])
            out = part if out is None else out + part
        return out

    def squared(self) -> "GrrRangeSplit":
        return self.replace(parts=tuple(p.squared() for p in self.parts))

    def plan_stats(self) -> dict:
        ps = [p.plan_stats() for p in self.parts]
        total = sum(s["entries"] for s in ps)
        coo = sum(s["coo"] for s in ps)
        spill = sum(s["coo"] + sum(s["overflow_levels"]) for s in ps)
        st = sum(s["supertiles"] for s in ps)
        return {
            "entries": total,
            "level1": sum(s["level1"] for s in ps),
            "overflow_levels": [sum(s["overflow_levels"]) for s in ps],
            "coo": coo,
            "coo_frac": coo / total if total else 0.0,
            "spill_frac": spill / total if total else 0.0,
            "supertiles": st,
            "cap": [s["cap"] for s in ps],
            "fill": (sum(s["level1"] for s in ps) / (st * SLOTS)
                     if st else 0.0),
            "bounds": list(self.bounds),
        }


DENSE_GRID_MIN_FILL = 0.7


def _maybe_dense_grid(G1, G2, G3, VALS, gw_of_st, ow_of_st, n_gw, n_ow,
                      force=None):
    """Reorder a built plan's tiles into the gw-major full (gw × ow_p)
    grid (see ``GrrDirection.dense_grid``) when the block grid is dense
    enough that the dummy tiles cost less than the revisiting kernel's
    per-tile overhead.  Returns (G1, G2, G3, VALS, gwg) or None (keep
    the legacy order)."""
    from photon_ml_tpu.ops.grr_kernel import DENSE_B

    n_ow_p = -(-n_ow // DENSE_B) * DENSE_B
    n_st_p = n_gw * n_ow_p
    n_st = VALS.shape[0]
    dense = (force if force is not None
             else n_st >= DENSE_GRID_MIN_FILL * n_st_p)
    if not dense:
        return None
    pos = (np.asarray(gw_of_st, np.int64) * n_ow_p
           + np.asarray(ow_of_st, np.int64))

    def scatter(a):
        out = np.zeros((n_st_p,) + a.shape[1:], a.dtype)
        out[pos] = a
        return out

    gwg = np.repeat(np.arange(n_gw, dtype=np.int32), n_ow_p // DENSE_B)
    return (scatter(np.asarray(G1)), scatter(np.asarray(G2)),
            scatter(np.asarray(G3)), scatter(np.asarray(VALS)), gwg)


def _spill_overflow(s_idx, s_seg, s_val, m_real, table_len, n_segments,
                    validate, threshold, device=True, depth=4):
    """Compile the COO spill into an overflow plan when it is big
    enough to matter; the chain recurses up to ``depth`` levels (the
    final level's residual stays COO).
    Operates on HOST arrays, before any device placement — pulling
    device arrays back would serialize the whole plan transfer into the
    build timeline.

    The level-2 cap is re-chosen by the occupancy heuristic on the
    spill subset — the spilled entries are exactly the heavy tail, so
    their mean group occupancy (and hence cap) is higher.  The plan is
    kept while its streamed slots stay under ~96 per absorbed entry
    (~1.2 KB ≈ 15 ns of HBM time at the measured kernel bandwidth, vs
    ~26 ns measured for the XLA scatter it replaces); beyond that the
    tail is too scattered to block and the COO fallback stays.

    Returns (overflow, s_idx, s_seg, s_val) — spill arrays emptied when
    absorbed."""
    if depth <= 0 or threshold is None or m_real <= threshold:
        return None, s_idx, s_seg, s_val
    # Cheap pre-check before paying for a level-2 build: every plan
    # carries at least ceil(n_segments/segwin) dummy supertiles, and the
    # widest segwin (smallest cap=4) bounds that floor from below.  A
    # tail that can't clear the 96-slots-per-entry bar even at the floor
    # would be built (multi-GB arrays, full routing) only to be thrown
    # away.
    st_floor = -(-n_segments // (WIN // 4))
    if st_floor * SLOTS > 96 * m_real:
        return None, s_idx, s_seg, s_val
    # The spill's own overflow threshold carries through (depth-capped:
    # a single mega-segment can otherwise absorb only ~cap*n_gw entries
    # per level while the economy checks keep passing — an unbounded
    # chain would recurse to a RecursionError).  Under power-law skew
    # each level absorbs ~2/3 of the remainder (measured at the KDD
    # shape: 16.3M -> 5.5M at one level), so the default 4 levels leave
    # only a trivial COO tail.  Each level passes the same pre-build
    # and 96-slots-per-entry economy checks.
    lvl2 = build_grr_direction(
        idx=np.asarray(s_idx[:m_real], np.int64),
        seg=np.asarray(s_seg[:m_real], np.int64),
        val=np.asarray(s_val[:m_real]),
        table_len=table_len, n_segments=n_segments,
        cap=None, validate=validate,
        overflow_threshold=(threshold if depth > 1 else None),
        device=device, overflow_depth=depth - 1,
    )
    if lvl2.n_supertiles * SLOTS > 96 * m_real:
        return None, s_idx, s_seg, s_val
    z = np.zeros(0, np.int32)
    return lvl2, z, z, np.zeros(0, np.float32)


def _native_direction(cols, vals_masked, direction, table_len, n_segments,
                      cap, validate, overflow_threshold,
                      device=True,
                      dense_grid=None,
                      idx_range=None) -> "GrrDirection | None":
    """One direction's plan via the C++ builder (``pml_grr_plan``), or
    None when the native library is unavailable / declines the shape.
    Rank assignment differs from the numpy path (scan order vs sort
    order) — both are valid plans; contractions agree (tested).

    ``device=False`` keeps the plan's leaves as host numpy arrays —
    the mesh-sharded build pads shard plans to a common shape on the
    host before placing each on its own device (one transfer, no
    device round-trip).  ``idx_range=(lo, hi)`` builds a column-range
    sub-plan: the C++ builder skips out-of-range entries in-stream (no
    extra numpy masking passes) and the returned plan contracts the
    table SLICE [lo, hi)."""
    from photon_ml_tpu.native import grr_plan_native, grr_routes_native

    conv = jnp.asarray if device else np.asarray
    plan = grr_plan_native(cols, vals_masked, direction, table_len,
                           n_segments, cap, idx_range=idx_range)
    if plan is None:
        return None
    if idx_range is not None:
        table_len = int(idx_range[1] - idx_range[0])
    routes = grr_routes_native(plan["dst"], plan["hi"])
    if routes is None:
        return None
    G1, G2, G3 = routes
    if validate and plan["vals"].shape[0]:
        _validate_routes(G2, G3)
    m = int(np.count_nonzero(plan["spill_val"]))
    total = m + int(np.count_nonzero(plan["vals"]))
    overflow, s_idx, s_seg, s_val = _spill_overflow(
        plan["spill_idx"], plan["spill_seg"], plan["spill_val"], m,
        table_len, n_segments, validate, overflow_threshold, device=device,
    )
    # Warn only about spill that STAYS on the XLA scatter path — spill
    # absorbed into the overflow plan runs at kernel speed and needs no
    # operator tuning.  Rate-limited: one summary per plan build.
    m_coo = int(np.count_nonzero(s_val))
    _spill_warnings.note(m_coo, total)
    VALS, gw_arr = plan["vals"], plan["gw_of_st"]
    ow_arr, first_arr = plan["ow_of_st"], plan["first_of_ow"]
    dg = _maybe_dense_grid(G1, G2, G3, VALS, gw_arr, ow_arr,
                           plan["n_gw"], plan["n_ow"], force=dense_grid)
    is_dense = dg is not None
    if is_dense:
        G1, G2, G3, VALS, gw_arr = dg
        ow_arr = first_arr = np.zeros(0, np.int32)
    return GrrDirection(
        g1=conv(G1), g2=conv(G2), g3=conv(G3),
        vals=conv(VALS),
        gw_of_st=conv(gw_arr),
        ow_of_st=conv(ow_arr),
        first_of_ow=conv(first_arr),
        spill_idx=conv(s_idx),
        spill_seg=conv(s_seg),
        spill_val=conv(s_val),
        table_len=table_len, n_segments=n_segments, cap=plan["cap"],
        n_gw=plan["n_gw"], n_ow=plan["n_ow"], overflow=overflow,
        dense_grid=is_dense,
    )


def build_grr_direction(
    idx: np.ndarray,
    seg: np.ndarray,
    val: np.ndarray,
    table_len: int,
    n_segments: int,
    cap: int | None = None,
    validate: bool = True,
    overflow_threshold: int | None = None,
    device: bool = True,
    dense_grid: bool | None = None,
    overflow_depth: int = 4,
) -> GrrDirection:
    """Compile one direction's plan from COO (idx, seg, val).

    Entries with val == 0 are dropped.  ``cap`` (slots per segment per
    table-window) defaults to a heuristic from the occupancy
    distribution; overflow spills to the COO fallback.
    ``device=False`` keeps leaves as host numpy (see _native_direction).
    """
    import time as _time

    from photon_ml_tpu.ops.crossbar import route_tile

    _t0 = _time.perf_counter()
    _mark = lambda name: (
        logger.debug("grr build %s: %.2fs", name,
                     _time.perf_counter() - _t0)
        if logger.isEnabledFor(logging.DEBUG) else None
    )
    idx = np.asarray(idx, np.int64)
    seg = np.asarray(seg, np.int64)
    val = np.asarray(val, np.float32)
    keep0 = val != 0
    if not bool(keep0.all()):  # skip three 10⁸-entry gathers when dense
        idx, seg, val = idx[keep0], seg[keep0], val[keep0]
    if idx.size and (idx.min() < 0 or idx.max() >= table_len):
        raise ValueError("idx out of range")
    if seg.size and (seg.min() < 0 or seg.max() >= n_segments):
        raise ValueError("seg out of range")

    _mark("drop-zeros")
    n_gw = max(1, -(-table_len // WIN))
    gw = idx // WIN

    # Capacity heuristic: cover ~1.5× the mean nonempty (seg, window)
    # occupancy; power of two in [4, 64].
    group_key = seg * n_gw + gw
    if cap is None:
        if idx.size:
            # Mean nonempty-(seg, window) occupancy.  Estimated from a
            # random sample of whole *segments* (sampling entries would
            # undercount every group and bias cap low); exact unique
            # over 10⁷+ keys would cost a full sort.
            if n_segments > 8192:
                segs = np.random.default_rng(0).choice(
                    n_segments, 4096, replace=False)
                # Membership via a boolean LUT — one O(nnz) gather,
                # vs. a binary search per entry.
                lut = np.zeros(n_segments, bool)
                lut[segs] = True
                samp = group_key[lut[seg]]
            else:
                samp = group_key
            _, counts = np.unique(samp, return_counts=True)
            mean = counts.mean() if counts.size else 1.0
            cap = int(np.clip(_next_pow2(int(np.ceil(1.5 * mean))), 4, 64))
        else:
            cap = 4
    if cap not in (1, 2, 4, 8, 16, 32, 64, 128):
        raise ValueError(f"cap must be a power of two ≤ 128, got {cap}")
    _mark("cap-heuristic")
    segwin = WIN // cap
    group = TILE // cap
    n_ow = max(1, -(-n_segments // segwin))

    # Slot rank within (seg, window); beyond cap → spill.
    q = _group_ranks(group_key)
    _mark("rank-q")
    spill1 = q >= cap

    ow = seg // segwin
    bk = ow * n_gw + gw                    # block key, sorted order = (ow, gw)
    # Start ROW = the entry's window sub-tile (idx%WIN)//128: the kernel
    # then gathers straight from the UNtransposed table window (row s
    # holds table[gw·WIN + s·128 ...]; the gather plane carries the lane
    # residue idx%128).
    hrow = (idx % WIN) // TILE

    # Start-lane rank within (block, start-row) among cap-kept entries;
    # beyond 128 starts per row → spill.
    k1 = ~spill1
    rank2 = np.full(idx.size, TILE, np.int64)
    rank2[k1] = _group_ranks(bk[k1] * TILE + hrow[k1])
    spill2 = k1 & (rank2 >= TILE)
    _mark("rank-rho")
    kept = k1 & ~spill2
    spilled = ~kept

    # Supertiles: one per non-empty block, plus a dummy per empty
    # segment-window (every ow needs ≥1 supertile so its output block
    # is written).
    bkk = bk[kept]
    if bkk.size and bool((bkk[1:] >= bkk[:-1]).all()):
        # Row-direction keys arrive sorted: unique = run boundaries,
        # no 10⁸-entry sort.
        blocks = bkk[np.r_[True, bkk[1:] != bkk[:-1]]]
    else:
        blocks = np.unique(bkk)
    present_ow = np.unique(blocks // n_gw) if blocks.size else np.empty(0, np.int64)
    missing_ow = np.setdiff1d(np.arange(n_ow, dtype=np.int64), present_ow)
    blocks = np.sort(np.r_[blocks, missing_ow * n_gw])
    n_st = blocks.size
    st_of = np.searchsorted(blocks, bkk)

    _mark("blocks")
    gw_of_st = (blocks % n_gw).astype(np.int32)
    ow_of_st = (blocks // n_gw).astype(np.int32)
    first_of_ow = np.r_[1, (np.diff(ow_of_st) != 0).astype(np.int32)].astype(
        np.int32
    )

    # Start and final positions (within each supertile).
    r_s = hrow[kept]
    l_s = rank2[kept]
    b = (seg[kept] % segwin)
    r_f = q[kept] * group + b // TILE
    l_f = b % TILE
    start_flat = st_of * SLOTS + r_s * TILE + l_s
    final_flat = st_of * SLOTS + r_f * TILE + l_f

    _mark("positions")
    hi = (idx[kept] % TILE).astype(np.int8)

    HI = np.zeros(n_st * SLOTS, np.int8)
    HI[start_flat] = hi
    VALS = np.zeros(n_st * SLOTS, np.float32)
    VALS[final_flat] = val[kept]

    # Destination-slot map: real elements start→final; padding starts
    # pair off with padding finals (both flat lists are sorted and have
    # equal per-supertile counts, so positions align by construction).
    _mark("scatter-hi-vals")
    dst = np.empty(n_st * SLOTS, np.int32)
    occ_s = np.zeros(n_st * SLOTS, bool)
    occ_s[start_flat] = True
    occ_f = np.zeros(n_st * SLOTS, bool)
    occ_f[final_flat] = True
    dst[start_flat] = (r_f * TILE + l_f).astype(np.int32)
    free_s = np.flatnonzero(~occ_s)
    free_f = np.flatnonzero(~occ_f)
    dst[free_s] = (free_f % SLOTS).astype(np.int32)
    _mark("pad-bijection")
    dst = dst.reshape(n_st, TILE, TILE)
    HI = HI.reshape(n_st, TILE, TILE)
    VALS = VALS.reshape(n_st, TILE, TILE)

    # Route every supertile; fuse route stage 1 into the gather index.
    # Native batched path (C++ pml_grr_routes) when available; the
    # Python loop below is the byte-identical-in-semantics fallback
    # (per-tile colorings may differ — both are proper, sums agree).
    from photon_ml_tpu.native import grr_routes_native

    native = grr_routes_native(dst, HI)
    if native is not None:
        G1, G2, G3 = native
    else:
        if n_st > 64:
            logger.warning(
                "GRR: routing %d supertiles with the pure-Python colorer "
                "(native library unavailable) — this is orders of "
                "magnitude slower than the C++ path", n_st,
            )
        G1 = np.empty((n_st, TILE, TILE), np.int8)
        G2 = np.empty((n_st, TILE, TILE), np.int8)
        G3 = np.empty((n_st, TILE, TILE), np.int8)
        for t in range(n_st):
            rg1, rg2, rg3 = route_tile(dst[t])
            G1[t] = np.take_along_axis(HI[t], rg1, axis=1).astype(np.int8)
            G2[t] = rg2.astype(np.int8)
            G3[t] = rg3.astype(np.int8)

    _mark("routes")
    if validate and n_st:
        _validate_routes(G2, G3)

    _mark("validate")
    # Spill COO, padded to a multiple of 8.
    s_idx = idx[spilled].astype(np.int32)
    s_seg = seg[spilled].astype(np.int32)
    s_val = val[spilled]
    m = s_idx.size
    if m:
        m_pad = -(-m // 8) * 8
        s_idx = np.pad(s_idx, (0, m_pad - m))
        s_seg = np.pad(s_seg, (0, m_pad - m))
        s_val = np.pad(s_val, (0, m_pad - m))

    overflow, s_idx, s_seg, s_val = _spill_overflow(
        s_idx, s_seg, s_val, m, table_len, n_segments, validate,
        overflow_threshold, device=device, depth=overflow_depth,
    )
    # Warn only about spill that stays on the XLA scatter path (spill
    # absorbed by the overflow plan runs at kernel speed).
    # Rate-limited: one summary per plan build.
    m_coo = int(np.count_nonzero(s_val))
    _spill_warnings.note(m_coo, max(idx.size, 1))
    _mark("spill")
    conv = jnp.asarray if device else np.asarray
    dg = _maybe_dense_grid(G1, G2, G3, VALS, gw_of_st, ow_of_st,
                           n_gw, n_ow, force=dense_grid)
    is_dense = dg is not None
    if is_dense:
        G1, G2, G3, VALS, gw_of_st = dg
        ow_of_st = first_of_ow = np.zeros(0, np.int32)
    return GrrDirection(
        g1=conv(G1), g2=conv(G2), g3=conv(G3),
        vals=conv(VALS),
        gw_of_st=conv(gw_of_st),
        ow_of_st=conv(ow_of_st),
        first_of_ow=conv(first_of_ow),
        spill_idx=conv(s_idx), spill_seg=conv(s_seg),
        spill_val=conv(s_val),
        table_len=table_len, n_segments=n_segments, cap=cap,
        n_gw=n_gw, n_ow=n_ow, overflow=overflow,
        dense_grid=is_dense,
    )


def _validate_routes(G2, G3) -> None:
    """Guard against an improper edge coloring silently corrupting the
    permutation (advisor finding): a proper coloring makes route stages
    2 and 3 true lane permutations, so every row of G2/G3 must contain
    each lane exactly once.  (Stage 1 is fused with the gather index and
    is validated semantically by the layout tests.)  Large plans are
    spot-checked on a 256-supertile sample to keep ETL time linear."""
    if G2.shape[0] > 256:
        sel = np.linspace(0, G2.shape[0] - 1, 256).astype(np.int64)
        G2, G3 = G2[sel], G3[sel]
    for name, G in (("g2", G2), ("g3", G3)):
        sorted_rows = np.sort(G.astype(np.int32), axis=2)
        if not np.array_equal(
            sorted_rows,
            np.broadcast_to(np.arange(TILE, dtype=np.int32), G.shape),
        ):
            raise AssertionError(
                f"GRR route stage {name} is not a lane permutation — "
                "improper edge coloring"
            )


def _select_hot(counts: np.ndarray, threshold: int,
                max_hot: int) -> np.ndarray:
    """Hot-column ids from occupancy counts (top-``max_hot`` above
    ``threshold``)."""
    hot = np.flatnonzero(counts > threshold)
    if hot.size > max_hot:
        order = np.argsort(counts[hot])[::-1]
        hot = np.sort(hot[order[:max_hot]])
    return hot


def _apply_hot_split(cols, vals, dim, n_rows, hot):
    """Densify a given hot id set out of an ELL batch →
    (x_hot [n_rows, H] f32, keep_mask [n, k])."""
    nz = vals != 0
    pos = np.full(dim, -1, np.int64)
    pos[hot] = np.arange(hot.size)
    is_hot = nz & (pos[cols] >= 0)
    x_hot = np.zeros((n_rows, hot.size), np.float32)
    r_idx, k_idx = np.nonzero(is_hot)
    np.add.at(x_hot, (r_idx, pos[cols[r_idx, k_idx]]), vals[r_idx, k_idx])
    return x_hot, nz & ~is_hot


def dense_hot_split(
    cols: np.ndarray,
    vals: np.ndarray,
    dim: int,
    n_rows: int,
    threshold: int | None = None,
    max_hot: int = 128,
):
    """Split hot columns out of an ELL batch for the dense MXU side.

    Returns (hot_ids [H] int32, x_hot [n_rows, H] f32, keep_mask [n,k])
    where keep_mask marks entries that stay sparse.
    """
    cols = np.asarray(cols)
    vals = np.asarray(vals, np.float32)
    counts = np.bincount(cols[vals != 0].reshape(-1), minlength=dim)
    if threshold is None:
        threshold = max(64, n_rows // 16)
    hot = _select_hot(counts, threshold, max_hot)
    x_hot, keep = _apply_hot_split(cols, vals, dim, n_rows, hot)
    return hot.astype(np.int32), x_hot, keep


@struct.dataclass
class GrrPair:
    """Both contraction directions + the dense hot-column side.

    The complete TPU-fast replacement for a sparse design matrix:
    ``dot``/``t_dot`` are X·v and Xᵀ·r with margins/gradients running
    through the GRR kernel and hot columns through one MXU matmul.

    Under power-law column popularity three column classes get three
    structures (the scale lesson — a 10⁸-nnz CTR dataset broke both
    extremes): MEGA-hot columns (denser than any per-window capacity)
    go to the dense [n, H] MXU side, but H is byte-budgeted — at 10⁷⁺
    rows each dense column costs 4n bytes of HBM; MID-hot columns
    (would overflow the tail plan's capacity everywhere, yet are far
    too sparse to afford densifying) get their own compact GRR plan
    ``col_mid`` over remapped ids [0, M) — restricting segments to just
    those M columns collapses the plan to ~1 segment-window, so a high
    cap fits them at a few slots/entry; the TAIL runs the main plan +
    level-2 overflow.  Only the gradient direction needs the mid split
    (segments = columns there); the row direction absorbs mid entries
    in its ordinary row groups.
    """

    row_dir: GrrDirection     # segments = rows, table = w-space
    col_dir: GrrDirection     # segments = TAIL cols, table = residual-space
    hot_ids: Array            # [H] i32
    x_hot: Array              # [n_rows, H] f32
    mid_ids: Array | None = None       # [M] i32 — mid-hot column ids
    col_mid: "GrrDirection | None" = None  # segments = mid cols (compact)

    @property
    def n_rows(self) -> int:
        return self.row_dir.n_segments

    @property
    def dim(self) -> int:
        return self.col_dir.n_segments

    def dot(self, w: Array) -> Array:
        """X·w — [n_rows] (margins / HVP forward side)."""
        return _grr_dot(self, w)

    def t_dot(self, r: Array) -> Array:
        """Xᵀ·r — [dim] (gradient side)."""
        return _grr_tdot(self, r)

    def squared(self) -> "GrrPair":
        return GrrPair(
            row_dir=self.row_dir.squared(),
            col_dir=self.col_dir.squared(),
            hot_ids=self.hot_ids,
            x_hot=self.x_hot * self.x_hot,
            mid_ids=self.mid_ids,
            col_mid=None if self.col_mid is None else self.col_mid.squared(),
        )


def _dot_impl(pair: GrrPair, w: Array) -> Array:
    out = pair.row_dir.contract(w)
    if pair.hot_ids.shape[0]:
        out = out + pair.x_hot @ w[pair.hot_ids]
    return out


def _tdot_impl(pair: GrrPair, r: Array) -> Array:
    out = pair.col_dir.contract(r)
    if pair.col_mid is not None:
        out = out.at[pair.mid_ids].add(pair.col_mid.contract(r))
    if pair.hot_ids.shape[0]:
        out = out.at[pair.hot_ids].add(pair.x_hot.T @ r)
    return out


def _grr_dot(pair: GrrPair, w: Array) -> Array:
    """X·w with a custom VJP (the contraction is linear; its transpose
    is the other direction's plan, so autodiff never sees the kernel)."""

    @jax.custom_vjp
    def f(w):
        return _dot_impl(pair, w)

    def fwd(w):
        return f(w), None

    def bwd(_, g):
        return (_tdot_impl(pair, g),)

    f.defvjp(fwd, bwd)
    return f(w)


def _grr_tdot(pair: GrrPair, r: Array) -> Array:
    @jax.custom_vjp
    def f(r):
        return _tdot_impl(pair, r)

    def fwd(r):
        return f(r), None

    def bwd(_, g):
        return (_dot_impl(pair, g),)

    f.defvjp(fwd, bwd)
    return f(r)


def _range_overflow_threshold(overflow_threshold: int,
                              frac: float) -> int:
    """Per-range overflow threshold: scales with the range's mass
    fraction (the global floor would leave a mid-size range's spill on
    the COO scatter) with a floor below which a level-2 plan can't pay
    for itself.  Single source for the resident AND sharded builders —
    their spill economics must not drift apart (review finding)."""
    return max(4096, int(overflow_threshold * frac))


def _plan_col_ranges(cols, vals_masked, dim, max_parts=4,
                     sample_rows=65536):
    """Window-aligned contiguous column ranges of roughly homogeneous
    per-(row, window) occupancy, for the row direction's range split
    (``GrrRangeSplit``).  Estimated from a strided row sample (full
    per-window group counting would cost a 10⁸-entry sort; occupancy
    profiles are stable under row sampling).  Returns a list of
    (lo_col, hi_col, mass_frac) with ≥2 entries (mass_frac = sampled
    share of nonzeros, for per-part overflow thresholds), or None when
    one capacity class covers every window (uniform data — no split)."""
    n_gw = -(-dim // WIN)
    n = cols.shape[0]
    if n_gw < 2 or n == 0:
        return None
    if n > sample_rows:
        stride = n // sample_rows
        c = cols[::stride][:sample_rows]
        v = vals_masked[::stride][:sample_rows]
    else:
        c, v = cols, vals_masked
    rows, ks = np.nonzero(v != 0)
    if rows.size == 0:
        return None
    gw = c[rows, ks].astype(np.int64) // WIN
    cnt = np.bincount(gw, minlength=n_gw).astype(np.float64)
    key = rows.astype(np.int64) * n_gw + gw
    grp = np.bincount(np.unique(key) % n_gw,
                      minlength=n_gw).astype(np.float64)

    def cap_of(cnt_s, grp_s):
        occ = cnt_s / max(grp_s, 1.0)
        return int(np.clip(_next_pow2(int(np.ceil(1.5 * max(occ, 1.0)))),
                           4, 64))

    caps = [cap_of(cnt[w], grp[w]) for w in range(n_gw)]
    # A partial trailing window's occupancy is lower only because the
    # window is narrower — treating it as its own capacity class would
    # split perfectly uniform data with unaligned dim (review finding).
    # Force it into its neighbor's run; its mass still pools there.
    if dim % WIN != 0 and n_gw >= 2:
        caps[-1] = caps[-2]
    # Runs of equal ideal cap → candidate ranges [lo_w, hi_w, cnt, grp].
    runs = []
    for w in range(n_gw):
        if runs and caps[w] == cap_of(runs[-1][2], runs[-1][3]):
            runs[-1][1] = w + 1
            runs[-1][2] += cnt[w]
            runs[-1][3] += grp[w]
        else:
            runs.append([w, w + 1, cnt[w], grp[w]])
    total = cnt.sum()

    def merge_pass(min_mass):
        """Merge the cheapest adjacent pair (mass-weighted cap
        mismatch), preferring to absorb below-``min_mass`` runs."""
        best, best_cost = None, None
        for i in range(len(runs) - 1):
            a, b = runs[i], runs[i + 1]
            la = np.log2(cap_of(a[2], a[3]))
            lb = np.log2(cap_of(b[2], b[3]))
            cost = min(a[2], b[2]) * abs(la - lb)
            if min(a[2], b[2]) < min_mass:
                cost = -1.0 / (1 + cost)  # tiny runs merge first
            if best_cost is None or cost < best_cost:
                best, best_cost = i, cost
        a, b = runs[best], runs[best + 1]
        runs[best] = [a[0], b[1], a[2] + b[2], a[3] + b[3]]
        del runs[best + 1]

    min_mass = total / 64.0  # a range under ~1.6% of entries can't pay
    while len(runs) > 1 and (
        len(runs) > max_parts
        or min(r[2] for r in runs) < min_mass
    ):
        merge_pass(min_mass)
    # Collapse adjacent ranges that converged to the same cap.
    i = 0
    while i < len(runs) - 1:
        if cap_of(runs[i][2], runs[i][3]) == cap_of(runs[i + 1][2],
                                                    runs[i + 1][3]):
            runs[i] = [runs[i][0], runs[i + 1][1],
                       runs[i][2] + runs[i + 1][2],
                       runs[i][3] + runs[i + 1][3]]
            del runs[i + 1]
        else:
            i += 1
    if len(runs) < 2:
        return None
    # A split only pays when the capacity classes are genuinely apart:
    # within a 2× spread the pooled global cap lands within one class
    # of every window (minor slot waste, no spill), and the extra
    # sub-plan build + per-step dispatch is pure cost.
    final_caps = [cap_of(r[2], r[3]) for r in runs]
    if max(final_caps) < 4 * min(final_caps):
        return None
    return [(r[0] * WIN, min(r[1] * WIN, dim), r[2] / total)
            for r in runs]


def _mid_hot_split(cols, vals_masked, dim, n, mid_threshold, validate,
                   overflow_threshold, device=True, mid=None, cap=None,
                   dense_grid=None):
    """Mid-hot column split for the gradient direction (see GrrPair
    docstring): columns whose per-row-window occupancy would overflow
    the tail plan's capacities get a compact GrrDirection over remapped
    ids.  ``mid``/``cap``/``dense_grid`` may be forced (the sharded
    build needs one global mid set and mesh-uniform plan structure).
    Returns (mid_ids [M] i32 | None, col_mid | None, vals_masked_tail).
    """
    nz = vals_masked != 0
    if mid is None:
        counts = np.bincount(cols[nz].reshape(-1), minlength=dim)
        mid = np.flatnonzero(counts > mid_threshold)
    if not mid.size:
        return None, None, vals_masked
    pos = np.full(dim, -1, np.int64)
    pos[mid] = np.arange(mid.size)
    is_mid = nz & (pos[cols] >= 0)
    r_idx, k_idx = np.nonzero(is_mid)
    col_mid = build_grr_direction(
        idx=r_idx.astype(np.int64),
        seg=pos[cols[r_idx, k_idx]],
        val=vals_masked[r_idx, k_idx],
        table_len=n, n_segments=int(mid.size), cap=cap,
        validate=validate, overflow_threshold=overflow_threshold,
        device=device, dense_grid=dense_grid,
    )
    tail = np.where(is_mid, np.float32(0.0), vals_masked)
    return mid.astype(np.int32), col_mid, tail


# Phase timings of the most recent ``build_grr_pair`` call (seconds).
# Written whole (no partial states); read by bench.py so the ETL number
# of record is self-diagnosing (round-4 verdict: the host-build vs
# device-transfer split explains captured-vs-claimed ETL discrepancies).
last_build_phases: dict = {}


def _pair_cache_path(cols, vals, dim, cache_dir, config: dict,
                     extra: tuple = ()) -> str:
    """Plan-cache file path for these exact inputs (see
    ``photon_ml_tpu.cache.plan_cache``).  The config key hashes the
    PASSED option values (None = "auto") — the auto heuristics are
    deterministic functions of the data, so keying the raw arguments
    is exact; ``validate`` is excluded (it never changes the plan).
    ``vals`` is fingerprinted through the same float32 cast the build
    applies, so a caller holding float64 values resolves the same path
    the build will actually read/write."""
    from photon_ml_tpu.cache import plan_cache

    fp = plan_cache.dataset_fingerprint(
        np.asarray(cols), np.asarray(vals, np.float32), dim, extra=extra)
    return plan_cache.plan_cache_path(
        cache_dir, fp, plan_cache.plan_config_key(**config))


# The build_grr_pair options that are part of plan semantics (and so of
# the cache key); ``validate`` is excluded — it never changes the plan.
_PLAN_OPTION_NAMES = ("cap", "hot_threshold", "max_hot", "max_hot_bytes",
                      "mid_threshold", "overflow_threshold",
                      "col_range_split")


def pair_cache_path_for(cols, vals, dim, cache_dir: str,
                        **overrides) -> str:
    """The cache-file path ``build_grr_pair(cols, vals, dim,
    **overrides)`` would read/write.  Option defaults are resolved from
    ``build_grr_pair``'s own signature, so external callers (the bench)
    never hold a copy that can drift out of sync with it."""
    import inspect

    sig = inspect.signature(build_grr_pair)
    config = {n: sig.parameters[n].default for n in _PLAN_OPTION_NAMES}
    unknown = set(overrides) - set(config)
    if unknown:
        raise TypeError(f"unknown plan options: {sorted(unknown)}")
    config.update(overrides)
    return _pair_cache_path(cols, vals, dim, cache_dir, config)


@_collect_spill_warnings
def build_grr_pair(
    cols: np.ndarray,
    vals: np.ndarray,
    dim: int,
    cap: int | None = None,
    hot_threshold: int | None = None,
    max_hot: int = 128,
    max_hot_bytes: int = 2 << 30,
    mid_threshold: int | None = None,
    validate: bool = True,
    overflow_threshold: int | None = None,
    col_range_split: bool | None = None,
    cache_dir: str | None = None,
    cache_rebuild: bool = False,
) -> GrrPair:
    """Compile an ELL batch ([n,k] cols/vals) into the full GRR plan.

    ``overflow_threshold`` (spill entries below which the level-2 plan
    is not worth building) defaults to nnz-scaled: a fixed 16k floor
    plus 1/256 of the nonzeros, so 10⁸-nnz datasets don't compile a
    multi-GB second level to absorb a relatively negligible tail
    (SURVEY §7 scale class; the 96-slots-per-entry economy bound in
    ``_spill_overflow`` still applies on top).  ``max_hot_bytes``
    bounds the dense hot side's HBM cost (each dense column is 4n
    bytes); ``mid_threshold`` (default 16 entries per row-window)
    routes columns too dense for the tail plan but below the dense
    cutoff to the compact ``col_mid`` plan.  ``col_range_split``
    (default: auto, on for batches ≥ one row window) partitions the
    row direction's table axis into per-capacity column ranges under
    skewed column popularity (``GrrRangeSplit``); uniform data keeps
    the single global plan either way.

    ``cache_dir`` (default ``$PHOTON_ML_TPU_PLAN_CACHE``) enables the
    on-disk plan cache: a hit replaces the whole host build with one
    load + device transfer (the warm path); a miss builds as usual and
    persists the host plan for the next run.  Phase timings in
    ``last_build_phases`` record which path ran (``cache_hit``).
    ``cache_rebuild`` skips the cache READ but still saves — how the
    bench keeps its cold-ETL number honest while warming the cache.
    """
    import time as _time

    cols = np.asarray(cols)
    vals = np.asarray(vals, np.float32)
    n, k = cols.shape
    phases: dict = {}
    _t0 = _time.perf_counter()
    global last_build_phases

    cache_dir = _resolve_cache_dir(cache_dir)
    cache_path = None
    if cache_dir is not None:
        _passed = locals()
        cache_path = _pair_cache_path(
            cols, vals, dim, cache_dir,
            {name: _passed[name] for name in _PLAN_OPTION_NAMES})
        phases["cache_lookup_s"] = _time.perf_counter() - _t0
        from photon_ml_tpu.cache import plan_cache

        t0 = _time.perf_counter()
        # place=device_put pipelines the disk read of later directions
        # under the async transfer of earlier ones.
        cached = (None if cache_rebuild
                  else plan_cache.load_plan(cache_path,
                                            place=jax.device_put))
        if cached is not None:
            phases["cache_hit"] = 1.0
            phases["cache_load_s"] = _time.perf_counter() - t0
            t0 = _time.perf_counter()
            pair = jax.device_put(cached)   # remaining host leaves
            jax.block_until_ready(pair)
            phases["transfer_fence_s"] = _time.perf_counter() - t0
            phases["total_s"] = _time.perf_counter() - _t0
            last_build_phases = phases
            logger.info("GRR plan cache hit: %s", cache_path)
            return pair
        phases["cache_hit"] = 0.0

    if overflow_threshold is None:
        overflow_threshold = 16384 + int(np.count_nonzero(vals)) // 256
    n_row_windows = max(1, -(-n // WIN))
    if hot_threshold is None:
        # A column denser than ~48 entries per row-window will overflow
        # even the largest per-window capacity (64) and spill its whole
        # mass; route such columns to the dense MXU side.  (For small n
        # this sweeps most columns dense — which is exactly right:
        # small-d problems ARE dense matmuls.)
        hot_threshold = min(max(64, n // 16), 48 * n_row_windows)
    max_hot = min(max_hot, max(1, max_hot_bytes // (4 * n)))
    hot_ids, x_hot, keep = dense_hot_split(
        cols, vals, dim, n, threshold=hot_threshold, max_hot=max_hot
    )
    vals_masked = np.where(keep, vals, np.float32(0.0))
    phases["hot_split_s"] = _time.perf_counter() - _t0
    auto_mid = mid_threshold is None
    if auto_mid:
        mid_threshold = 16 * n_row_windows
    # Pipelined build: every independent host build — one task per row
    # range (or the single row plan) plus the (mid split → tail col)
    # chain — runs through ONE shared thread pool.  The C++ builder and
    # numpy release the GIL, so a multi-core TPU host builds all tasks
    # concurrently, targeting wall-clock ≈ one scan (this 1-core build
    # box is measured neutral).  Each task device_puts its OWN finished
    # plan immediately (PJRT copies asynchronously in the background),
    # so host→HBM transfers overlap the remaining host builds — the
    # mid plan's transfer starts before the tail col build finishes,
    # and early row ranges transfer under late ones.  The final fence
    # is timed separately (``last_build_phases``).
    from concurrent.futures import ThreadPoolExecutor

    # Range planning is a sampled scan (fast) — run it up front so the
    # task list is flat and the pool can be sized to it.
    split = (col_range_split if col_range_split is not None
             else n >= WIN)
    ranges = (_plan_col_ranges(cols, vals_masked, dim)
              if split else None)

    row_t0 = _time.perf_counter()

    def row_part(rng_):
        lo, hi, frac = rng_
        thr = _range_overflow_threshold(overflow_threshold, frac)
        p = _build_direction_ell(cols, vals_masked, 0, dim, n, cap,
                                 validate, thr, device=False,
                                 idx_range=(lo, hi))
        return p, jax.device_put(p)

    def row_single():
        p = _build_direction_ell(cols, vals_masked, 0, dim, n, cap,
                                 validate, overflow_threshold,
                                 device=False)
        return p, jax.device_put(p)

    def col_chain():
        # The auto heuristic skips the mid split below one full row
        # window: the compact plan's start-lane capacity (n starts per
        # block) is smaller than the mid mass it would carry, and tiny
        # batches belong to the dense/hot side anyway.  An explicit
        # mid_threshold overrides (tests, tuned workloads).
        t0 = _time.perf_counter()
        if not auto_mid or n >= WIN:
            mid_ids_h, col_mid_h, vals_tail = _mid_hot_split(
                cols, vals_masked, dim, n, mid_threshold, validate,
                overflow_threshold, device=False)
        else:
            mid_ids_h, col_mid_h, vals_tail = None, None, vals_masked
        # Transfer the mid plan under the tail col build.
        mid_ids_d = (None if mid_ids_h is None
                     else jax.device_put(mid_ids_h))
        col_mid_d = (None if col_mid_h is None
                     else jax.device_put(col_mid_h))
        phases["mid_split_s"] = _time.perf_counter() - t0
        t0 = _time.perf_counter()
        col_h = _build_direction_ell(cols, vals_tail, 1, n, dim, cap,
                                     validate, overflow_threshold,
                                     device=False)
        phases["col_build_s"] = _time.perf_counter() - t0
        return ((mid_ids_h, col_mid_h, col_h),
                (mid_ids_d, col_mid_d, jax.device_put(col_h)))

    n_row_tasks = len(ranges) if ranges else 1
    with ThreadPoolExecutor(max_workers=n_row_tasks + 1) as ex:
        f_col = ex.submit(col_chain)
        if ranges:
            row_futs = [ex.submit(row_part, r) for r in ranges]
        else:
            row_futs = [ex.submit(row_single)]
        row_results = [f.result() for f in row_futs]
        phases["row_build_s"] = _time.perf_counter() - row_t0
        (mid_ids_h, col_mid_h, col_h), \
            (mid_ids, col_mid, col_dir) = f_col.result()

    if ranges:
        bounds = tuple(lo for lo, _, _ in ranges) + (ranges[-1][1],)
        row_h = GrrRangeSplit(
            parts=tuple(p for p, _ in row_results), bounds=bounds,
            table_len=dim, n_segments=n)
        row_dir = GrrRangeSplit(
            parts=tuple(d for _, d in row_results), bounds=bounds,
            table_len=dim, n_segments=n)
        logger.info(
            "GRR row direction: column-range split into %d parts "
            "(bounds %s, caps %s)", len(ranges), bounds,
            [p.cap for p, _ in row_results])
    else:
        row_h, row_dir = row_results[0]

    pair = GrrPair(
        row_dir=row_dir, col_dir=col_dir,
        hot_ids=jnp.asarray(hot_ids), x_hot=jnp.asarray(x_hot),
        mid_ids=mid_ids,
        col_mid=col_mid,
    )
    if cache_path is not None:
        # Persist the HOST copy (no device pull-back) while the device
        # transfers drain; failures only cost the next run its warm
        # path, never this run.
        t0 = _time.perf_counter()
        try:
            from photon_ml_tpu.cache import plan_cache

            plan_cache.save_plan(cache_path, GrrPair(
                row_dir=row_h, col_dir=col_h,
                hot_ids=hot_ids, x_hot=x_hot,
                mid_ids=mid_ids_h, col_mid=col_mid_h))
        except Exception as e:  # never let the cache fail the run
            logger.warning("plan cache: save failed (%r)", e)
        phases["cache_save_s"] = _time.perf_counter() - t0
    t0 = _time.perf_counter()
    jax.block_until_ready(pair)
    phases["transfer_fence_s"] = _time.perf_counter() - t0
    phases["total_s"] = _time.perf_counter() - _t0
    last_build_phases = phases
    return pair


def _build_direction_ell(cols, vals_masked, direction, table_len,
                         n_segments, cap, validate, overflow_threshold,
                         device=True, dense_grid=None,
                         idx_range=None) -> GrrDirection:
    """One direction straight from (hot-masked) ELL arrays: native C++
    builder first, numpy COO path as the fallback.  ``idx_range``
    restricts to a table sub-range (column-range split; see
    ``GrrRangeSplit``)."""
    d = _native_direction(cols, vals_masked, direction, table_len,
                          n_segments, cap, validate, overflow_threshold,
                          device=device, dense_grid=dense_grid,
                          idx_range=idx_range)
    if d is not None:
        return d
    r_idx, k_idx = np.nonzero(vals_masked != 0)
    c = cols[r_idx, k_idx].astype(np.int64)
    v = vals_masked[r_idx, k_idx]
    idx, seg = ((c, r_idx.astype(np.int64)) if direction == 0
                else (r_idx.astype(np.int64), c))
    if idx_range is not None:
        lo, hi = idx_range
        if idx.size and (idx.min() < 0 or idx.max() >= table_len):
            raise ValueError("idx out of range")
        keep = (idx >= lo) & (idx < hi)
        idx, seg, v = idx[keep] - lo, seg[keep], v[keep]
        table_len = int(hi - lo)
    return build_grr_direction(
        idx=idx, seg=seg, val=v, table_len=table_len,
        n_segments=n_segments, cap=cap, validate=validate,
        overflow_threshold=overflow_threshold, device=device,
        dense_grid=dense_grid,
    )


# ---------------------------------------------------------------------------
# Mesh-sharded plans: per-device GrrPairs with mesh-uniform structure.
#
# Under data parallelism each device owns a contiguous row shard; its
# row_dir contracts the replicated w over local rows and its col_dir
# produces the [dim] gradient PARTIAL that the distributed objective's
# existing psum combines — the same contract the colmajor sharding
# satisfies, now at kernel speed (the north star's "pmapped Pallas
# kernel over an HBM-sharded CSR + ICI allreduce", BASELINE.json).
#
# jax assembles the shards into one global array per leaf
# (make_array_from_single_device_arrays), which requires every shard's
# pytree to have IDENTICAL structure and leaf shapes.  Three things are
# therefore forced mesh-uniform at build time:
#   * cap (static metadata, per direction): chosen by shard 0's
#     occupancy heuristic, reused by all shards;
#   * the hot-column set: computed from GLOBAL column counts so every
#     shard's dense side has the same [H] ids (each with its own rows);
#   * the two-level overflow: decided on the POOLED spill count, built
#     per shard with a common level-2 cap, or for nobody.
# Remaining shape differences (supertile count, spill length) are
# closed by padding with zero-valued dummy supertiles / COO entries,
# which contribute exactly zero to the contraction.
# ---------------------------------------------------------------------------


def _pad_grr_direction(d: GrrDirection, n_st: int, n_spill: int,
                       ovf_pad=None) -> GrrDirection:
    """Pad a host-built plan to (n_st supertiles, n_spill COO entries).

    Dummy supertiles carry vals=0 (zero contribution), gw=0 (any valid
    window), ow=n_ow-1 with first_of_ow=0 — appended after the real
    tiles they extend the last output-window run, so the kernel's
    accumulate-in-VMEM grid order stays valid."""
    rep = {}
    add = n_st - d.n_supertiles
    if d.dense_grid and add:
        raise AssertionError(
            "dense-grid shard plans must have equal tile counts "
            "(full grid); got a mismatch"
        )
    if add:
        z3 = lambda a, dt: np.concatenate(
            [np.asarray(a), np.zeros((add,) + np.asarray(a).shape[1:], dt)])
        rep.update(
            g1=z3(d.g1, np.int8), g2=z3(d.g2, np.int8), g3=z3(d.g3, np.int8),
            vals=z3(d.vals, np.float32),
            gw_of_st=np.concatenate(
                [np.asarray(d.gw_of_st), np.zeros(add, np.int32)]),
            ow_of_st=np.concatenate(
                [np.asarray(d.ow_of_st),
                 np.full(add, d.n_ow - 1, np.int32)]),
            first_of_ow=np.concatenate(
                [np.asarray(d.first_of_ow), np.zeros(add, np.int32)]),
        )
    madd = n_spill - d.n_spill
    if madd:
        rep.update(
            spill_idx=np.pad(np.asarray(d.spill_idx), (0, madd)),
            spill_seg=np.pad(np.asarray(d.spill_seg), (0, madd)),
            spill_val=np.pad(np.asarray(d.spill_val), (0, madd)),
        )
    if ovf_pad is not None and d.overflow is not None:
        rep["overflow"] = _pad_grr_direction(d.overflow, *ovf_pad)
    return d.replace(**rep) if rep else d


def _pool_overflow(dirs: list, table_len: int, n_segments: int,
                   validate: bool, threshold: int | None) -> list:
    """The sharded build's two-level-overflow decision, made once on the
    pooled spill (all-or-none, so shard pytrees stay congruent).  Same
    economics as ``_spill_overflow``: absorb the heavy tail at kernel
    speed while the level-2 plans stream < ~96 slots per entry."""
    ms = [int(np.count_nonzero(np.asarray(d.spill_val))) for d in dirs]
    total = sum(ms)
    if threshold is None or total <= threshold:
        return dirs
    st_floor = -(-n_segments // (WIN // 4))
    if st_floor * SLOTS * len(dirs) > 96 * total:
        return dirs
    order = sorted(range(len(dirs)), key=lambda i: -ms[i])
    l2cap = None
    l2dense = None
    lvl2: list = [None] * len(dirs)
    for i in order:
        d = dirs[i]
        lvl2[i] = build_grr_direction(
            idx=np.asarray(d.spill_idx, np.int64),
            seg=np.asarray(d.spill_seg, np.int64),
            val=np.asarray(d.spill_val),
            table_len=table_len, n_segments=n_segments, cap=l2cap,
            validate=validate, overflow_threshold=None, device=False,
            dense_grid=l2dense,
        )
        if l2cap is None:
            l2cap = lvl2[i].cap
            l2dense = lvl2[i].dense_grid
    if sum(x.n_supertiles for x in lvl2) * SLOTS > 96 * total:
        return dirs
    z = np.zeros(0, np.int32)
    return [
        d.replace(overflow=l2, spill_idx=z, spill_seg=z,
                  spill_val=np.zeros(0, np.float32))
        for d, l2 in zip(dirs, lvl2)
    ]


def _pad_dirs_common(dirs: list) -> list:
    """Pad every shard's plan (and level-2 plan) to the max shapes."""
    n_st = max(d.n_supertiles for d in dirs)
    n_sp = max(d.n_spill for d in dirs)
    ovf_pad = None
    if dirs[0].overflow is not None:  # all-or-none by construction
        ovf_pad = (max(d.overflow.n_supertiles for d in dirs),
                   max(d.overflow.n_spill for d in dirs))
    return [_pad_grr_direction(d, n_st, n_sp, ovf_pad) for d in dirs]


@_collect_spill_warnings
def build_sharded_grr_pairs(
    shard_cols: list[np.ndarray],
    shard_vals: list[np.ndarray],
    dim: int,
    cap: int | None = None,
    hot_threshold: int | None = None,
    max_hot: int = 128,
    max_hot_bytes: int = 2 << 30,
    mid_threshold: int | None = None,
    validate: bool = True,
    overflow_threshold: int | None = None,
    col_range_split: bool | None = None,
    cache_dir: str | None = None,
) -> list[GrrPair]:
    """Compile per-shard GRR plans over equal-size row shards.

    ``shard_cols``/``shard_vals``: one [per, k] ELL pair per device
    (already padded to equal row counts).  Returns one ``GrrPair`` per
    shard with HOST (numpy) leaves and identical pytree structure +
    leaf shapes, ready for ``jax.make_array_from_single_device_arrays``
    assembly (``parallel.mesh.shard_sparse_batch(layout="grr")``).
    ``col_range_split`` (default: auto, on for shards ≥ one row window)
    splits every shard's row direction into the SAME per-capacity
    column ranges under skewed column popularity (``GrrRangeSplit``),
    decided on a pooled cross-shard sample.

    ``cache_dir`` (default ``$PHOTON_ML_TPU_PLAN_CACHE``): on-disk plan
    cache over the whole shard list — the chunked builder's plans are
    the scale path's biggest host cost, and the congruent list
    round-trips as one entry (host leaves in, host leaves out).
    """
    n_shards = len(shard_cols)
    cache_dir = _resolve_cache_dir(cache_dir)
    cache_path = None
    if cache_dir is not None:
        from photon_ml_tpu.cache import plan_cache

        _passed = locals()
        config = {name: _passed[name] for name in _PLAN_OPTION_NAMES}
        config.update({"n_shards": n_shards, "sharded": True})
        cache_path = _pair_cache_path(
            shard_cols[0], shard_vals[0], dim, cache_dir, config,
            extra=tuple(shard_cols[1:]) + tuple(shard_vals[1:]))
        cached = plan_cache.load_plan(cache_path)
        if cached is not None:
            logger.info("sharded GRR plan cache hit: %s", cache_path)
            return cached
    per = shard_cols[0].shape[0]
    n_total = per * n_shards
    if overflow_threshold is None:   # nnz-scaled, as in build_grr_pair
        nnz = sum(int(np.count_nonzero(np.asarray(v))) for v in shard_vals)
        overflow_threshold = 16384 + nnz // 256

    # Global hot-column split: one hot id set for every shard.
    counts = np.zeros(dim, np.int64)
    for c, v in zip(shard_cols, shard_vals):
        nz = np.asarray(v) != 0
        counts += np.bincount(
            np.asarray(c)[nz].reshape(-1), minlength=dim)
    n_row_windows = max(1, -(-per // WIN)) * n_shards
    if hot_threshold is None:
        # Same economics as build_grr_pair, scaled to the shard-local
        # col_dir window count (a column overflows per-shard windows).
        hot_threshold = min(max(64, n_total // 16), 48 * n_row_windows)
    # Byte budget applies to each DEVICE's x_hot shard [per, H].
    max_hot = min(max_hot, max(1, max_hot_bytes // (4 * per)))
    hot = _select_hot(counts, hot_threshold, max_hot)
    hot_ids = hot.astype(np.int32)

    # Global mid-hot set (GrrPair docstring): forced common across
    # shards so the pytrees stay congruent.
    auto_mid = mid_threshold is None
    if auto_mid:
        mid_threshold = 16 * n_row_windows
    counts_nonhot = counts.copy()
    counts_nonhot[hot] = 0
    # Same one-full-row-window guard as build_grr_pair (start-lane
    # capacity of the compact plan scales with shard rows); explicit
    # mid_threshold overrides.
    mid = (np.flatnonzero(counts_nonhot > mid_threshold)
           if (not auto_mid or per >= WIN) else np.zeros(0, np.int64))
    mid_ids = mid.astype(np.int32) if mid.size else None
    mid_pos = None
    if mid.size:
        mid_pos = np.full(dim, -1, np.int64)
        mid_pos[mid] = np.arange(mid.size)

    # Pass 1: hot/mid masking per shard (+ per-shard mid mass, so the
    # mid cap is seeded by a shard that actually CARRIES mid entries —
    # the global mid set can be concentrated in a few shards, and an
    # empty shard's heuristic cap would doom the others to spill).
    prepped, mid_counts = [], []
    for c, v in zip(shard_cols, shard_vals):
        c = np.asarray(c)
        v = np.asarray(v, np.float32)
        x_hot, keep = _apply_hot_split(c, v, dim, per, hot)
        vm = np.where(keep, v, np.float32(0.0))
        prepped.append((c, x_hot, vm))
        mid_counts.append(
            0 if mid_pos is None
            else int(((vm != 0) & (mid_pos[c] >= 0)).sum()))

    # Pass 2: mid plans, heaviest shard first (cap/dense seeding).
    mid_dirs: list = [None] * n_shards
    tails: list = [None] * n_shards
    m_cap = m_dense = None
    if mid_pos is not None:
        for i in sorted(range(n_shards), key=lambda j: -mid_counts[j]):
            c, _, vm = prepped[i]
            _, md, tail = _mid_hot_split(
                c, vm, dim, per, mid_threshold, validate, None,
                device=False, mid=mid, cap=m_cap, dense_grid=m_dense,
            )
            m_cap = m_cap or md.cap
            m_dense = md.dense_grid if m_dense is None else m_dense
            mid_dirs[i] = md
            tails[i] = tail

    # Column-range split for the row direction (``GrrRangeSplit``):
    # decided ONCE on a pooled cross-shard sample so every shard splits
    # into the same ranges (congruence), with per-range caps/dense
    # flags forced common across shards like every other shared choice.
    row_ranges = None
    if col_range_split or (col_range_split is None and per >= WIN):
        samp_per = max(1, 65536 // n_shards)
        stride = max(1, per // samp_per)
        samp_c = np.concatenate(
            [c[::stride][:samp_per] for (c, _, _) in prepped])
        samp_v = np.concatenate(
            [vm[::stride][:samp_per] for (_, _, vm) in prepped])
        row_ranges = _plan_col_ranges(samp_c, samp_v, dim,
                                      sample_rows=samp_c.shape[0])
        if row_ranges:
            logger.info(
                "sharded GRR row direction: column-range split into %d "
                "parts (bounds %s)", len(row_ranges),
                [lo for lo, _, _ in row_ranges] + [dim])

    # Pass 3: main directions per shard, heaviest shard first — the
    # shared cap/dense-grid choice is seeded by the shard with the most
    # nonzeros, matching the Pass 2 rationale (advisor finding: seeding
    # from shard 0 in index order lets an unrepresentative shard pick a
    # too-small cap and push other shards' mass into spill/overflow).
    row_dirs: list = [None] * n_shards
    col_dirs: list = [None] * n_shards
    x_hots = [x_hot for (_, x_hot, _) in prepped]
    nnzs = [int(np.count_nonzero(vm)) for (_, _, vm) in prepped]
    n_parts = len(row_ranges) if row_ranges else 0
    row_parts: list = [[None] * n_parts for _ in range(n_shards)]
    part_caps = [cap] * n_parts
    part_dense: list = [None] * n_parts
    row_cap, col_cap = cap, cap
    row_dense = col_dense = None
    for i in sorted(range(n_shards), key=lambda j: -nnzs[j]):
        c, _, vm = prepped[i]
        vm_tail = tails[i] if tails[i] is not None else vm
        if row_ranges:
            for r, (lo, hi, _) in enumerate(row_ranges):
                p = _build_direction_ell(
                    c, vm, 0, dim, per, part_caps[r], validate, None,
                    device=False, dense_grid=part_dense[r],
                    idx_range=(lo, hi))
                part_caps[r] = part_caps[r] or p.cap
                part_dense[r] = (p.dense_grid if part_dense[r] is None
                                 else part_dense[r])
                row_parts[i][r] = p
        else:
            rd = _build_direction_ell(c, vm, 0, dim, per, row_cap,
                                      validate, None, device=False,
                                      dense_grid=row_dense)
            row_cap = row_cap or rd.cap
            row_dense = rd.dense_grid if row_dense is None else row_dense
            row_dirs[i] = rd
        cd_ = _build_direction_ell(c, vm_tail, 1, per, dim, col_cap,
                                   validate, None, device=False,
                                   dense_grid=col_dense)
        col_cap = col_cap or cd_.cap
        col_dense = cd_.dense_grid if col_dense is None else col_dense
        col_dirs[i] = cd_

    if row_ranges:
        # Overflow pooling + padding happen PER RANGE across shards
        # (each range is its own congruent plan family); the part-mass
        # fraction scales its overflow threshold as in build_grr_pair.
        bounds = tuple(lo for lo, _, _ in row_ranges) + (dim,)
        for r, (lo, hi, frac) in enumerate(row_ranges):
            fam = [row_parts[i][r] for i in range(n_shards)]
            thr = _range_overflow_threshold(overflow_threshold, frac)
            fam = _pool_overflow(fam, hi - lo, per, validate, thr)
            fam = _pad_dirs_common(fam)
            for i in range(n_shards):
                row_parts[i][r] = fam[i]
        row_dirs = [
            GrrRangeSplit(parts=tuple(row_parts[i]), bounds=bounds,
                          table_len=dim, n_segments=per)
            for i in range(n_shards)
        ]
    else:
        row_dirs = _pool_overflow(row_dirs, dim, per, validate,
                                  overflow_threshold)
        row_dirs = _pad_dirs_common(row_dirs)
    col_dirs = _pool_overflow(col_dirs, per, dim, validate,
                              overflow_threshold)
    col_dirs = _pad_dirs_common(col_dirs)
    if mid_pos is not None:
        mid_dirs = _pool_overflow(mid_dirs, per, int(mid.size), validate,
                                  overflow_threshold)
        mid_dirs = _pad_dirs_common(mid_dirs)
    pairs = [
        GrrPair(row_dir=rd, col_dir=cd_, hot_ids=hot_ids.copy(),
                x_hot=xh,
                mid_ids=None if mid_ids is None else mid_ids.copy(),
                col_mid=md)
        for rd, cd_, xh, md in zip(row_dirs, col_dirs, x_hots, mid_dirs)
    ]
    if cache_path is not None:
        try:
            from photon_ml_tpu.cache import plan_cache

            plan_cache.save_plan(cache_path, pairs)
        except Exception as e:  # never let the cache fail the run
            logger.warning("plan cache: save failed (%r)", e)
    return pairs
