"""Chunked batches: the beyond-HBM-residency training class.

Reference counterpart: Spark never holds a dataset on one machine — it
streams HDFS splits through executors and recomputes from lineage, so
the trainable size is bounded by the CLUSTER, not one host (SURVEY.md
§1 L1, §5.8 [expected structure, mount unavailable]).  The resident
TPU path inverts that trade: a compiled GRR plan must live in HBM for
the whole fit (~1.6 GB per 10⁶ examples measured, PERF.md), capping a
16 GB v5e chip at ~9×10⁶ examples.

This module removes the cap the same way Spark does — by streaming —
while keeping every FLOP on the TPU: the dataset is compiled ONCE into
K congruent chunk batches (identical pytree structure and leaf shapes,
the same trick the mesh-sharded build uses for multi-device
congruence), and every objective evaluation streams chunks through HBM,
accumulating (loss, gradient, HVP, Hessian-diagonal) partials on
device.  Every data-side quantity the GLM objective computes is a
linear reduction over examples, so chunked accumulation is EXACT up to
float-summation reordering (tested against the resident path).

Because the chunks are congruent, the per-chunk device program compiles
once and replays K times per pass; ``optim.streaming`` double-buffers
the host→device transfer of chunk i+1 under chunk i's compute, and
keeps up to ``max_resident`` chunks live in HBM so datasets that DO fit
pay the transfer once (the resident and streaming regimes are one code
path).

Layouts per chunk (``layout=``):
- ``"grr"`` — compiled GRR plans (``data.grr.build_sharded_grr_pairs``,
  chunks-as-shards): kernel-speed steps; ~1.6 GB/10⁶ examples streamed
  per pass — right when host↔device bandwidth is PCIe-class.
- ``"ell"`` — plain ELL (8 bytes/nnz): XLA gather/scatter steps, ~20×
  smaller stream; right when transfer dominates (or when even the ELL
  no longer fits and streaming volume is the binding cost).

With ``mesh=``, chunks × shards compose: each chunk is built as
congruent PER-DEVICE sub-batches (one more level of the same
congruence) and assembled onto the mesh per use; gradient partials
then meet in the distributed objective's existing psum.
"""

from __future__ import annotations

import dataclasses
import logging

import numpy as np

from photon_ml_tpu.data.batch import SparseBatch

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class ChunkedBatch:
    """K congruent host-resident chunk batches over one example axis.

    ``chunks[i]`` is a ``SparseBatch`` with HOST (numpy) leaves — or,
    when ``mesh`` is set, a list of per-device host sub-batches to be
    assembled example-sharded on use.  All chunks have identical pytree
    structure and leaf shapes (one compile serves all).
    """

    chunks: list
    dim: int
    n: int                 # real examples (before padding)
    chunk_rows: int        # examples per chunk (last chunk padded)
    layout: str
    mesh: object | None = None   # jax.sharding.Mesh | None

    @property
    def n_chunks(self) -> int:
        return len(self.chunks)

    def chunk_slice(self, i: int) -> tuple[int, int]:
        """Real-example range [lo, hi) covered by chunk i."""
        lo = i * self.chunk_rows
        return lo, min(lo + self.chunk_rows, self.n)

    def set_offsets(self, offsets: np.ndarray) -> None:
        """Install new per-example offsets (GAME coordinate-descent
        residual passing) into the host chunks, zero-padded to the
        chunk grid.  Callers holding device copies must invalidate
        them (``optim.streaming.ChunkedGLMObjective.invalidate``)."""
        offsets = np.asarray(offsets, np.float32)
        if offsets.shape[0] != self.n:
            raise ValueError(
                f"offsets length {offsets.shape[0]} != n {self.n}")
        for i in range(self.n_chunks):
            lo, hi = self.chunk_slice(i)
            pad = np.zeros(self.chunk_rows, np.float32)
            pad[: hi - lo] = offsets[lo:hi]
            if self.mesh is None:
                self.chunks[i] = self.chunks[i].replace(offsets=pad)
            else:
                per = self.chunk_rows // len(self.chunks[i])
                self.chunks[i] = [
                    b.replace(offsets=pad[j * per:(j + 1) * per])
                    for j, b in enumerate(self.chunks[i])
                ]


def _host_chunk(cols, vals, labels, weights, offsets, mask, dim,
                grr=None) -> SparseBatch:
    """A SparseBatch with host numpy leaves (no device placement)."""
    return SparseBatch(
        values=np.asarray(vals, np.float32),
        col_ids=np.asarray(cols, np.int32),
        labels=np.asarray(labels, np.float32),
        weights=np.asarray(weights, np.float32),
        offsets=np.asarray(offsets, np.float32),
        mask=np.asarray(mask, np.float32),
        dim=dim,
        grr=grr,
    )


def build_chunked_batch(
    rows,
    dim: int,
    labels: np.ndarray,
    weights: np.ndarray | None = None,
    offsets: np.ndarray | None = None,
    chunk_rows: int | None = None,
    n_chunks: int | None = None,
    layout: str = "grr",
    mesh=None,
    row_capacity: int | None = None,
    drop_ell_with_grr: bool = True,
    cache_dir: str | None = None,
) -> ChunkedBatch:
    """Compile a dataset into K congruent host chunk batches.

    ``rows``: ``SparseRows`` (scale path) or list of (col_ids, values)
    pairs.  Exactly one of ``chunk_rows`` / ``n_chunks`` must be given.
    ``layout``: "grr" or "ell" (see module docstring).  With ``mesh``,
    each chunk is split further into one congruent sub-batch per mesh
    device (chunks × shards).

    The GRR chunk plans are built by the SAME congruent-shapes builder
    the mesh path uses (chunks are shards of the example axis either
    way); hot/mid column sets and capacities are global across chunks,
    so one compiled contraction program serves every chunk.
    ``cache_dir`` enables the on-disk plan cache for those chunk plans
    (``photon_ml_tpu.cache``): the scale run's plan compile is paid
    once per dataset, not once per run.
    """
    from photon_ml_tpu.data.sparse_rows import SparseRows

    if not isinstance(rows, SparseRows):
        rows = SparseRows.from_rows(rows)
    if layout not in ("grr", "ell"):
        raise ValueError(f"unknown chunk layout {layout!r} "
                         "(supported: 'grr', 'ell')")
    n = len(labels)
    if (chunk_rows is None) == (n_chunks is None):
        raise ValueError("give exactly one of chunk_rows / n_chunks")
    n_dev = 1 if mesh is None else mesh.devices.size
    if n_chunks is not None:
        chunk_rows = -(-n // n_chunks)
    # Pieces must be equal-size: round chunk_rows up to the device grid.
    chunk_rows = -(-chunk_rows // n_dev) * n_dev
    n_chunks = -(-n // chunk_rows)
    per = chunk_rows // n_dev
    n_pieces = n_chunks * n_dev

    weights = np.ones(n, np.float32) if weights is None else np.asarray(
        weights, np.float32)
    offsets = np.zeros(n, np.float32) if offsets is None else np.asarray(
        offsets, np.float32)
    labels = np.asarray(labels, np.float32)
    k = row_capacity if row_capacity is not None else max(rows.max_nnz, 1)

    def piece_arrays(p):
        lo = p * per
        hi = min(lo + per, n)
        if lo >= n:
            cols_p = np.zeros((per, k), np.int32)
            vals_p = np.zeros((per, k), np.float32)
            aux = [np.zeros(per, np.float32)] * 4
            return cols_p, vals_p, aux
        cols_p, vals_p = rows[lo:hi].to_ell(row_capacity=k, pad_to=per)
        pad1 = lambda a: np.pad(
            np.asarray(a[lo:hi], np.float32), (0, per - (hi - lo)))
        mask = np.zeros(per, np.float32)
        mask[: hi - lo] = 1.0
        return cols_p, vals_p, [pad1(labels), pad1(weights),
                                pad1(offsets), mask]

    pieces_arr = [piece_arrays(p) for p in range(n_pieces)]

    grr_pairs = [None] * n_pieces
    if layout == "grr":
        from photon_ml_tpu.data.grr import build_sharded_grr_pairs

        grr_pairs = build_sharded_grr_pairs(
            [c for c, _, _ in pieces_arr],
            [v for _, v, _ in pieces_arr],
            dim,
            cache_dir=cache_dir,
        )

    pieces = []
    for (cols_p, vals_p, (lab, wt, off, mask)), pair in zip(pieces_arr,
                                                            grr_pairs):
        if pair is not None and drop_ell_with_grr:
            # The plan serves every contraction; the ELL copy would
            # only add 8 bytes/nnz to every chunk transfer.
            cols_p = np.zeros((per, 0), np.int32)
            vals_p = np.zeros((per, 0), np.float32)
        pieces.append(_host_chunk(cols_p, vals_p, lab, wt, off, mask,
                                  dim, grr=pair))

    if mesh is None:
        chunks = pieces
    else:
        chunks = [pieces[i * n_dev:(i + 1) * n_dev]
                  for i in range(n_chunks)]
    logger.info(
        "chunked batch: n=%d -> %d chunks x %d rows (%s%s)", n, n_chunks,
        chunk_rows, layout, f", {n_dev}-device mesh" if mesh else "")
    return ChunkedBatch(chunks=chunks, dim=dim, n=n,
                        chunk_rows=chunk_rows, layout=layout, mesh=mesh)
