"""Chunked batches: the beyond-HBM-residency training class.

Reference counterpart: Spark never holds a dataset on one machine — it
streams HDFS splits through executors and recomputes from lineage, so
the trainable size is bounded by the CLUSTER, not one host (SURVEY.md
§1 L1, §5.8 [expected structure, mount unavailable]).  The resident
TPU path inverts that trade: a compiled GRR plan must live in HBM for
the whole fit (~1.6 GB per 10⁶ examples measured, PERF.md), capping a
16 GB v5e chip at ~9×10⁶ examples.

This module removes the cap the same way Spark does — by streaming —
while keeping every FLOP on the TPU: the dataset is compiled ONCE into
K congruent chunk batches (identical pytree structure and leaf shapes,
the same trick the mesh-sharded build uses for multi-device
congruence), and every objective evaluation streams chunks through HBM,
accumulating (loss, gradient, HVP, Hessian-diagonal) partials on
device.  Every data-side quantity the GLM objective computes is a
linear reduction over examples, so chunked accumulation is EXACT up to
float-summation reordering (tested against the resident path).

Because the chunks are congruent, the per-chunk device program compiles
once and replays K times per pass; ``optim.streaming`` double-buffers
the host→device transfer of chunk i+1 under chunk i's compute, and
keeps up to ``max_resident`` chunks live in HBM so datasets that DO fit
pay the transfer once (the resident and streaming regimes are one code
path).

Three residency tiers (round 8 completes the set):

1. **HBM** — ``max_resident`` device chunks (``optim.streaming``).
2. **Host RAM** — without ``spill_dir``, every chunk lives as numpy
   leaves in ``chunks`` (bounded by host RAM: 26.4 GB at 3×10⁷
   examples, the round-5 wall).
3. **Disk** — with ``spill_dir`` (``$PHOTON_ML_TPU_SPILL_DIR`` is
   honored by the config/estimator layer, not here),
   chunks spill to atomic per-chunk ``.npz`` files
   (``data.chunk_store``) and at most ``host_max_resident`` decoded
   chunks stay live (memory-mapped, LRU) — host RSS is bounded by the
   WINDOW, dataset size by disk, and ``optim.streaming``'s prefetch
   thread overlaps disk read → host staging → async device_put of
   chunks i+1..i+depth under chunk i's compute.  Offsets (GAME CD
   residual state) stay OUT of the spilled payload — ``chunk(i)``
   overlays the live window — so ``set_offsets`` is an O(n) host write
   and spilled files double as persistent warm-ETL artifacts across
   runs.

Layouts per chunk (``layout=``):
- ``"grr"`` — compiled GRR plans (``data.grr.build_sharded_grr_pairs``,
  chunks-as-shards): kernel-speed steps; ~1.6 GB/10⁶ examples streamed
  per pass — right when host↔device bandwidth is PCIe-class.
- ``"ell"`` — plain ELL (8 bytes/nnz): XLA gather/scatter steps, ~20×
  smaller stream; right when transfer dominates (or when even the ELL
  no longer fits and streaming volume is the binding cost).

With ``mesh=``, chunks × shards compose: each chunk is built as
congruent PER-DEVICE sub-batches (one more level of the same
congruence) and assembled onto the mesh per use; gradient partials
then meet in the distributed objective's existing psum.
"""

from __future__ import annotations

import dataclasses
import logging

import numpy as np

from photon_ml_tpu.data.batch import SparseBatch

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class ChunkedBatch:
    """K congruent chunk batches over one example axis.

    Resident mode (``store`` is None): ``chunks[i]`` is a
    ``SparseBatch`` with HOST (numpy) leaves — or, when ``mesh`` is
    set, a list of per-device host sub-batches to be assembled
    example-sharded on use.  Spilled mode (``store`` set): ``chunks``
    holds placeholders and ``chunk(i)`` pulls from the disk-backed LRU
    window, overlaying the current ``offsets_host`` slice.  All chunks
    have identical pytree structure and leaf shapes (one compile
    serves all) either way; consumers go through ``chunk(i)``.
    """

    chunks: list
    dim: int
    n: int                 # real examples (before padding)
    chunk_rows: int        # examples per chunk (last chunk padded)
    layout: str
    mesh: object | None = None   # jax.sharding.Mesh | None
    store: object | None = None  # data.chunk_store.ChunkStore | None
    # Spilled mode: offsets over the FULL padded chunk grid
    # [n_chunks·chunk_rows] — CD-iteration state kept out of the
    # spilled payload so chunk files survive ``set_offsets``.
    offsets_host: np.ndarray | None = None
    # Fleet mode (parallel.fleet): the contiguous chunk shard THIS host
    # owns, and its sentinel-padded chunk-synchronized schedule (same
    # length on every host).  None = single-host run, every chunk.
    local_chunk_ids: list | None = None
    schedule: list | None = None

    @property
    def n_chunks(self) -> int:
        return len(self.chunks)

    @property
    def owned_chunk_ids(self) -> list:
        """Chunk ids this host streams (all of them outside a fleet)."""
        if self.local_chunk_ids is None:
            return list(range(self.n_chunks))
        return list(self.local_chunk_ids)

    @property
    def chunk_schedule(self) -> list:
        """The per-host chunk visit order: owned chunks first, then
        ``fleet.EMPTY_CHUNK`` sentinels padding ragged shards to the
        fleet-common step count (single-host: just every chunk)."""
        if self.schedule is None:
            return list(range(self.n_chunks))
        return list(self.schedule)

    def chunk_slice(self, i: int) -> tuple[int, int]:
        """Real-example range [lo, hi) covered by chunk i."""
        lo = i * self.chunk_rows
        return lo, min(lo + self.chunk_rows, self.n)

    def chunk(self, i: int):
        """Host pieces of chunk i, current offsets installed — the one
        accessor every consumer uses (resident list or spill store)."""
        if self.store is None:
            return self.chunks[i]
        c = self.store.get(i)
        off = self.offsets_host[i * self.chunk_rows:
                                (i + 1) * self.chunk_rows]
        if self.mesh is None:
            return c.replace(offsets=off)
        per = self.chunk_rows // len(c)
        return [b.replace(offsets=off[j * per:(j + 1) * per])
                for j, b in enumerate(c)]

    def set_offsets(self, offsets: np.ndarray) -> None:
        """Install new per-example offsets (GAME coordinate-descent
        residual passing), zero-padded to the chunk grid.  Resident
        mode rewrites the host chunks; spilled mode only rewrites the
        external offsets window (chunk files are offset-free).  Callers
        holding device copies must invalidate them
        (``optim.streaming.ChunkedGLMObjective.invalidate``)."""
        offsets = np.asarray(offsets, np.float32)
        if offsets.shape[0] != self.n:
            raise ValueError(
                f"offsets length {offsets.shape[0]} != n {self.n}")
        if self.store is not None:
            self.offsets_host = np.zeros(
                self.n_chunks * self.chunk_rows, np.float32)
            self.offsets_host[: self.n] = offsets
            return
        for i in range(self.n_chunks):
            lo, hi = self.chunk_slice(i)
            pad = np.zeros(self.chunk_rows, np.float32)
            pad[: hi - lo] = offsets[lo:hi]
            if self.mesh is None:
                self.chunks[i] = self.chunks[i].replace(offsets=pad)
            else:
                per = self.chunk_rows // len(self.chunks[i])
                self.chunks[i] = [
                    b.replace(offsets=pad[j * per:(j + 1) * per])
                    for j, b in enumerate(self.chunks[i])
                ]


def _host_chunk(cols, vals, labels, weights, offsets, mask, dim,
                grr=None) -> SparseBatch:
    """A SparseBatch with host numpy leaves (no device placement)."""
    return SparseBatch(
        values=np.asarray(vals, np.float32),
        col_ids=np.asarray(cols, np.int32),
        labels=np.asarray(labels, np.float32),
        weights=np.asarray(weights, np.float32),
        offsets=np.asarray(offsets, np.float32),
        mask=np.asarray(mask, np.float32),
        dim=dim,
        grr=grr,
    )


def build_chunked_batch(
    rows,
    dim: int,
    labels: np.ndarray,
    weights: np.ndarray | None = None,
    offsets: np.ndarray | None = None,
    chunk_rows: int | None = None,
    n_chunks: int | None = None,
    layout: str = "grr",
    mesh=None,
    row_capacity: int | None = None,
    drop_ell_with_grr: bool = True,
    cache_dir: str | None = None,
    spill_dir: str | None = None,
    host_max_resident: int = 2,
) -> ChunkedBatch:
    """Compile a dataset into K congruent chunk batches.

    ``rows``: ``SparseRows`` (scale path) or list of (col_ids, values)
    pairs.  Exactly one of ``chunk_rows`` / ``n_chunks`` must be given.
    ``layout``: "grr" or "ell" (see module docstring).  With ``mesh``,
    each chunk is split further into one congruent sub-batch per mesh
    device (chunks × shards).

    The GRR chunk plans are built by the SAME congruent-shapes builder
    the mesh path uses (chunks are shards of the example axis either
    way); hot/mid column sets and capacities are global across chunks,
    so one compiled contraction program serves every chunk.
    ``cache_dir`` enables the on-disk plan cache for those chunk plans
    (``photon_ml_tpu.cache``): the scale run's plan compile is paid
    once per dataset, not once per run.

    ``spill_dir`` (None = stay host-resident) activates the disk tier
    (``data.chunk_store``).  Deliberately EXPLICIT at this layer — the
    ``$PHOTON_ML_TPU_SPILL_DIR`` default is applied by the config/
    estimator layer, so library callers building a resident baseline
    (bench control arms, parity tests) cannot be silently flipped to
    the spill store by ambient environment.  With the disk tier on:
    chunks spill to atomic content-keyed ``.npz`` files and at most
    ``host_max_resident`` decoded chunks stay live.  ELL chunks are
    built AND spilled one at a time, so peak RSS during ETL is bounded
    by the window too; GRR chunk plans need the global congruent build
    first (shared hot/mid sets, pooled overflow, common padding), so
    their ETL peak is one full plan set — they spill right after and
    steady-state RSS is bounded either way.  A chunk file that already
    exists for the same content key is NOT rebuilt (warm ETL); a
    missing or corrupt file at sweep time rebuilds from ``rows``
    (lineage), so the store can never fail a run.
    """
    from photon_ml_tpu.data.grr import collect_spill_warnings
    from photon_ml_tpu.data.sparse_rows import SparseRows

    if not isinstance(rows, SparseRows):
        rows = SparseRows.from_rows(rows)
    if layout not in ("grr", "ell"):
        raise ValueError(f"unknown chunk layout {layout!r} "
                         "(supported: 'grr', 'ell')")
    n = len(labels)
    if (chunk_rows is None) == (n_chunks is None):
        raise ValueError("give exactly one of chunk_rows / n_chunks")
    n_dev = 1 if mesh is None else mesh.devices.size
    if n_chunks is not None:
        chunk_rows = -(-n // n_chunks)
    # Pieces must be equal-size: round chunk_rows up to the device grid.
    chunk_rows = -(-chunk_rows // n_dev) * n_dev
    n_chunks = -(-n // chunk_rows)
    per = chunk_rows // n_dev

    # Fleet mode: this host builds/spills/streams ONLY its contiguous
    # chunk shard; ids stay global (the full grid is the coordinate
    # system for offsets and checkpoints).
    from photon_ml_tpu.parallel import fleet as _fleet

    fctx = _fleet.active()
    local_ids = schedule = None
    if fctx is not None and fctx.is_fleet:
        local_ids, schedule = _fleet.shard_chunk_ids(
            n_chunks, fctx.host_id, fctx.n_hosts)

    weights = np.ones(n, np.float32) if weights is None else np.asarray(
        weights, np.float32)
    offsets = np.zeros(n, np.float32) if offsets is None else np.asarray(
        offsets, np.float32)
    labels = np.asarray(labels, np.float32)
    k = row_capacity if row_capacity is not None else max(rows.max_nnz, 1)

    def piece_arrays(p):
        lo = p * per
        hi = min(lo + per, n)
        if lo >= n:
            cols_p = np.zeros((per, k), np.int32)
            vals_p = np.zeros((per, k), np.float32)
            aux = [np.zeros(per, np.float32)] * 4
            return cols_p, vals_p, aux
        cols_p, vals_p = rows[lo:hi].to_ell(row_capacity=k, pad_to=per)
        pad1 = lambda a: np.pad(
            np.asarray(a[lo:hi], np.float32), (0, per - (hi - lo)))
        mask = np.zeros(per, np.float32)
        mask[: hi - lo] = 1.0
        return cols_p, vals_p, [pad1(labels), pad1(weights),
                                pad1(offsets), mask]

    def make_pieces(pieces_arr, grr_pairs, zero_offsets=False):
        pieces = []
        for (cols_p, vals_p, (lab, wt, off, mask)), pair in zip(
                pieces_arr, grr_pairs):
            if pair is not None and drop_ell_with_grr:
                # The plan serves every contraction; the ELL copy would
                # only add 8 bytes/nnz to every chunk transfer.
                cols_p = np.zeros((per, 0), np.int32)
                vals_p = np.zeros((per, 0), np.float32)
            if zero_offsets:
                off = np.zeros(per, np.float32)
            pieces.append(_host_chunk(cols_p, vals_p, lab, wt, off,
                                      mask, dim, grr=pair))
        return pieces

    def group(pieces):
        if mesh is None:
            return pieces
        return [pieces[i * n_dev:(i + 1) * n_dev]
                for i in range(len(pieces) // n_dev)]

    def compile_all(zero_offsets=False, chunk_ids=None):
        """Build the given chunks (default: all) → {chunk_id: chunk}.
        A fleet host passes its shard — GRR hot/mid congruence is then
        per-host, which is sound (the plan layout is a per-chunk
        program detail; only the dim-indexed coefficients are global)
        and keeps ETL cost proportional to the shard."""
        ids = list(range(n_chunks)) if chunk_ids is None else list(chunk_ids)
        ps = [p for i in ids for p in range(i * n_dev, (i + 1) * n_dev)]
        pieces_arr = [piece_arrays(p) for p in ps]
        grr_pairs = [None] * len(ps)
        if layout == "grr":
            from photon_ml_tpu.data.grr import build_sharded_grr_pairs

            grr_pairs = build_sharded_grr_pairs(
                [c for c, _, _ in pieces_arr],
                [v for _, v, _ in pieces_arr],
                dim,
                cache_dir=cache_dir,
            )
        return dict(zip(ids, group(make_pieces(pieces_arr, grr_pairs,
                                               zero_offsets))))

    if spill_dir is not None:
        # Per-host spill subdir: fleet hosts never share chunk files
        # (each opens/spills only its shard, and two hosts on one
        # machine must not race the same window accounting).
        spill_dir = _fleet.host_dir(spill_dir, fctx)
        # Unwritable spill dir DEGRADES to the resident build with one
        # warning (ISSUE 9): losing the disk tier costs memory bound,
        # not the run.
        from photon_ml_tpu.data.chunk_store import probe_spill_dir

        spill_dir = probe_spill_dir(spill_dir)

    if spill_dir is None:
        # One aggregation scope around the whole sharded build: every
        # per-shard sub-plan's spill note folds into ONE summary line
        # (ISSUE 4 satellite — MULTICHIP_r05's tail was 15+ lines).
        with collect_spill_warnings():
            built = compile_all(chunk_ids=local_ids)
        chunks = [built.get(i) for i in range(n_chunks)]
        logger.info(
            "chunked batch: n=%d -> %d chunks x %d rows (%s%s)%s", n,
            n_chunks, chunk_rows, layout,
            f", {n_dev}-device mesh" if mesh else "",
            (f", host {fctx.host_id}/{fctx.n_hosts} shard "
             f"{len(built)} chunks") if local_ids is not None else "")
        return ChunkedBatch(chunks=chunks, dim=dim, n=n,
                            chunk_rows=chunk_rows, layout=layout,
                            mesh=mesh, local_chunk_ids=local_ids,
                            schedule=schedule)

    # -- spilled build: disk tier on, host RSS bounded by the window --
    from photon_ml_tpu.data.chunk_store import ChunkStore, store_key

    key = store_key(rows, labels, weights, dim, chunk_rows=chunk_rows,
                    layout=layout, n_dev=n_dev, row_capacity=k,
                    drop_ell_with_grr=drop_ell_with_grr)

    def build_chunk_ell(i):
        """One ELL chunk, independently of the others (congruence is
        by construction: shared k / per / padding grid)."""
        ps = range(i * n_dev, (i + 1) * n_dev)
        pieces = make_pieces([piece_arrays(p) for p in ps],
                             [None] * n_dev, zero_offsets=True)
        return pieces if mesh is not None else pieces[0]

    def rebuild(i):
        """Lineage fallback for a missing/corrupt chunk file."""
        if layout == "ell":
            return build_chunk_ell(i)
        # GRR congruence (shared hot/mid sets, pooled overflow, common
        # padding) is a GLOBAL property of this host's plan set:
        # rebuilding one chunk means rebuilding the set (the plan cache
        # makes this one load when cache_dir is set).  Heal every
        # missing sibling while the set is in hand.
        built = compile_all(zero_offsets=True, chunk_ids=local_ids)
        for j, ch in built.items():
            if j != i and not store.has(j):
                store.put(j, ch, keep_resident=False)
        return built[i]

    store = ChunkStore(spill_dir, key, n_chunks,
                       host_max_resident=host_max_resident,
                       rebuild=rebuild)
    owned = list(range(n_chunks)) if local_ids is None else local_ids
    missing = [i for i in owned if not store.has(i)]
    with collect_spill_warnings():   # one summary per sharded build
        if missing and layout == "ell":
            # Build-time spill: one chunk in flight at a time — ETL
            # peak RSS is (window + 1) chunks, not the dataset.
            for i in missing:
                store.put(i, build_chunk_ell(i))
        elif missing:
            built = compile_all(zero_offsets=True, chunk_ids=local_ids)
            for i in missing:
                store.put(i, built[i])
    if missing:
        from photon_ml_tpu.data.chunk_store import release_free_heap

        release_free_heap()   # build churn must not read as steady RSS
    offsets_host = np.zeros(n_chunks * chunk_rows, np.float32)
    offsets_host[:n] = offsets
    logger.info(
        "chunked batch: n=%d -> %d chunks x %d rows (%s%s), spilled to "
        "%s (%d built, %d reused; host window %d)%s", n, n_chunks,
        chunk_rows, layout, f", {n_dev}-device mesh" if mesh else "",
        spill_dir, len(missing), len(owned) - len(missing),
        store.host_max_resident,
        (f", host {fctx.host_id}/{fctx.n_hosts} shard "
         f"{len(owned)} chunks") if local_ids is not None else "")
    return ChunkedBatch(chunks=[None] * n_chunks, dim=dim, n=n,
                        chunk_rows=chunk_rows, layout=layout, mesh=mesh,
                        store=store, offsets_host=offsets_host,
                        local_chunk_ids=local_ids, schedule=schedule)
