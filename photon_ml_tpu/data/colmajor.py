"""Column-major (transposed-ELL) layout: scatter-free gradient contraction.

Reference counterpart: none — the reference's ``ValueAndGradientAggregator``
(photon-lib ``com.linkedin.photon.ml.function.glm`` [expected path, mount
unavailable — see SURVEY.md §2.2]) accumulates ``grad += ℓ'·x`` example by
example in a JVM fold, where scattered writes are cheap.  On TPU the same
contraction ``g = Xᵀ r`` expressed over the row-major ELL layout is a
30M-element scatter-add (``segment_sum``), which XLA serializes — measured
at ~1 GB/s effective HBM bandwidth on v5e, ~500× off the roofline.

The TPU-first fix is a *layout*, not a kernel: store a second, transposed
copy of the design matrix so the gradient reads, rather than writes,
irregularly:

    g[j] = Σ_k tvals[j,k] · r[trows[j,k]]        (gather + row-sum)

which is the exact dual of the margin pass ``m[i] = Σ_k v[i,k]·w[c[i,k]]``.
Both directions then hit the same fast gather+reduce pipeline (XLA's, or
the Pallas kernel in ``ops/pallas_kernels.py``).

Entity/feature skew (power-law nnz per column) is handled by **virtual-row
splitting**: every column is chopped into ⌈nnz_j / C⌉ virtual rows of a
fixed capacity C, and a final *tiny* sorted ``segment_sum`` over the ~V
virtual rows (V ≈ nnz/C + #cols, ~100–1000× smaller than nnz) folds the
partial sums into ``g``.  This keeps shapes static (XLA requirement),
bounds padding waste regardless of skew, and replaces the O(nnz) scatter
with an O(V) one.

The transpose costs one extra copy of the nonzeros in HBM and a one-time
host-side sort — the rebuild's analog of Spark's one-time ``partitionBy``
shuffle (SURVEY.md §5.8): layout work happens once, not per iteration.
Under data parallelism each device carries the transpose of *its own* row
shard (``trows`` are shard-local), so the per-device partial gradients are
still combined by one ``psum`` — see ``parallel.mesh.shard_sparse_batch``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

Array = jax.Array


@struct.dataclass
class ColMajorSlice:
    """Transposed-ELL arrays for one row shard.

    ``tvals/trows``: [V, C] — virtual rows of capacity C; ``trows`` are
    row indices *local to the paired row batch*.  Padding slots carry
    ``tvals == 0`` and point at row 0, so they add exact zeros.
    ``vcol``: [V] — the (sorted, possibly repeated) output column of each
    virtual row; padding virtual rows point at column 0 with all-zero
    values.
    """

    tvals: Array   # [V, C] float
    trows: Array   # [V, C] int32 (local row ids)
    vcol: Array    # [V] int32, sorted
    dim: int = struct.field(pytree_node=False)

    @property
    def n_virtual_rows(self) -> int:
        return self.tvals.shape[-2]

    @property
    def capacity(self) -> int:
        return self.tvals.shape[-1]

    def xt_dot(self, r: Array) -> Array:
        """Xᵀ r without a large scatter: gather r, row-sum, tiny fold.

        Note this XLA formulation still pays XLA's scalar gather; it
        exists as the mesh-shardable fallback.  The fast TPU path is the
        GRR layout (``data.grr``), which replaces both this and the
        row-major gather with Mosaic lane-gather kernels.
        """
        from photon_ml_tpu.ops.kernels import gather_rowsum

        part = gather_rowsum(r, self.tvals, self.trows)       # [V]
        return jax.ops.segment_sum(
            part, self.vcol, num_segments=self.dim, indices_are_sorted=True
        )

    def squared(self) -> "ColMajorSlice":
        """Values → values² (for Hessian-diagonal aggregation)."""
        return self.replace(tvals=self.tvals * self.tvals)


def choose_capacity(counts: np.ndarray) -> int:
    """Virtual-row capacity heuristic: cover the 75th-percentile column in
    one virtual row, clamped to [8, 512] and rounded up to a multiple of
    8 (f32 sublane count — keeps tiles aligned)."""
    nz = counts[counts > 0]
    if nz.size == 0:
        return 8
    c = int(np.percentile(nz, 75.0))
    c = max(8, min(512, c))
    return int((c + 7) // 8 * 8)


def build_colmajor(
    col_ids: np.ndarray,
    values: np.ndarray,
    dim: int,
    capacity: int | None = None,
    pad_vrows_to_multiple: int | None = None,
    pad_vrows_to: int | None = None,
) -> ColMajorSlice:
    """Build the transposed-ELL arrays from host-side row-ELL arrays.

    Args:
      col_ids: [n, k] int — row-major ELL column ids (padding slots may
        repeat real ids; they must carry value 0).
      values: [n, k] float — matching values; entries with value 0 are
        dropped (they contribute nothing to any contraction).
      dim: feature-space width.
      capacity: virtual-row capacity C (default: ``choose_capacity``).
      pad_vrows_to_multiple: pad V up so row tiles stay aligned
        (default: multiple of 8, the f32 sublane count).
      pad_vrows_to: pad V to exactly this (for equal-shape shards under
        data parallelism — ``parallel.mesh.shard_sparse_batch``).
    """
    n, k = col_ids.shape
    counts_all = None
    if capacity is None:
        counts_all = np.bincount(
            np.asarray(col_ids).reshape(-1)[
                np.asarray(values).reshape(-1) != 0
            ],
            minlength=dim,
        )
        capacity = choose_capacity(counts_all)

    # Native counting-sort build (O(nnz + dim), C++) when available;
    # byte-identical output to the numpy path below.
    from photon_ml_tpu.native import colmajor_build_native

    native = colmajor_build_native(
        np.asarray(col_ids), np.asarray(values), dim, capacity,
        pad_vrows_to_multiple=pad_vrows_to_multiple,
        pad_vrows_to=pad_vrows_to,
    )
    if native is not None:
        tvals, trows, vcol = native
        return ColMajorSlice(
            tvals=jnp.asarray(tvals),
            trows=jnp.asarray(trows),
            vcol=jnp.asarray(vcol),
            dim=dim,
        )

    flat_c = np.asarray(col_ids).reshape(-1)
    flat_v = np.asarray(values).reshape(-1)
    flat_r = np.repeat(np.arange(n, dtype=np.int64), k)

    keep = flat_v != 0
    flat_c, flat_v, flat_r = flat_c[keep], flat_v[keep], flat_r[keep]

    order = np.argsort(flat_c, kind="stable")
    sc = flat_c[order]
    sv = flat_v[order]
    sr = flat_r[order]

    counts = (
        counts_all
        if counts_all is not None
        else np.bincount(sc, minlength=dim)
    )
    C = capacity

    vrows_per_col = -(-counts // C)                     # ceil, 0 for empty
    vrow_base = np.zeros(dim + 1, np.int64)
    np.cumsum(vrows_per_col, out=vrow_base[1:])
    V = int(vrow_base[-1])
    from photon_ml_tpu.ops.kernels import vrow_pad

    V_pad = vrow_pad(V, pad_vrows_to_multiple)
    if pad_vrows_to is not None:
        if pad_vrows_to < V:
            raise ValueError(f"pad_vrows_to={pad_vrows_to} < V={V}")
        V_pad = pad_vrows_to

    offs = np.zeros(dim + 1, np.int64)
    np.cumsum(counts, out=offs[1:])
    pos = np.arange(sc.size, dtype=np.int64) - offs[sc]  # rank within column
    vidx = vrow_base[sc] + pos // C
    slot = pos % C

    tvals = np.zeros((V_pad, C), np.float32)
    trows = np.zeros((V_pad, C), np.int32)
    tvals[vidx, slot] = sv
    trows[vidx, slot] = sr
    vcol = np.zeros(V_pad, np.int32)
    vcol[:V] = np.repeat(
        np.arange(dim, dtype=np.int32), vrows_per_col.astype(np.int64)
    )

    return ColMajorSlice(
        tvals=jnp.asarray(tvals),
        trows=jnp.asarray(trows),
        vcol=jnp.asarray(vcol),
        dim=dim,
    )
