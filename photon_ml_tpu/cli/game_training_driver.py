"""GAME training driver: config file → trained, evaluated, saved models.

Reference counterpart: ``GameTrainingDriver``
(photon-client ``com.linkedin.photon.ml.cli.game.training`` [expected
path, mount unavailable — see SURVEY.md §2.8/§3.1]): parse params,
prepare feature maps, read train/validation data, build datasets, run
``GameEstimator.fit`` over the optimization grid, select/save models.

Usage::

    python -m photon_ml_tpu.cli.game_training_driver --config cfg.json

The classic single-GLM path (reference's legacy ``Driver``) is the
degenerate case: one fixed-effect coordinate, LIBSVM input — exactly how
the reference folded its pre-GAME trainer into GAME (SURVEY §3.3).
"""

from __future__ import annotations

import argparse
import json
import os

import numpy as np

from photon_ml_tpu.config import (
    CoordinateKind,
    TrainingConfig,
    config_to_json,
    load_training_config,
)
from photon_ml_tpu.estimators.game_estimator import FitResult, GameEstimator
from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.io.dataset import (
    build_index_maps,
    detect_format,
    read_game_dataset,
)
from photon_ml_tpu.io.index_map import load_index_maps, save_index_maps
from photon_ml_tpu.io.libsvm import read_libsvm
from photon_ml_tpu.io.model_io import save_game_model
from photon_ml_tpu.utils.run_log import DEFAULT_FLUSH_EVERY_S, RunLogger


def _read_libsvm_dataset(path: str, config: TrainingConfig,
                         n_features: int | None = None) -> GameDataset:
    """LIBSVM → single-shard GameDataset (a1a-class fixtures, §3.3)."""
    fixed = [c for c in config.coordinates
             if c.kind == CoordinateKind.FIXED_EFFECT]
    if len(config.coordinates) != 1 or not fixed:
        raise ValueError(
            "LIBSVM input supports exactly one fixed-effect coordinate; "
            "use JSONL records for GAME configs"
        )
    shard = fixed[0].feature_shard
    rows, labels, dim = read_libsvm(path, n_features=n_features)
    return GameDataset(
        labels=labels, features={shard: rows}, entity_ids={},
        feature_dims={shard: dim},
    )


def prepare_data(config: TrainingConfig, log: RunLogger):
    """Read (+ index) train/validation data; the driver's ETL phase.

    Returns (train, validation, feature_maps, entity_maps); maps are
    None for LIBSVM input (indices are literal in the file).
    """
    fmt = detect_format(config.input_path, config.input_format)
    feature_maps = entity_maps = None
    if fmt == "libsvm":
        with log.timed("read_training_data", format=fmt):
            train = _read_libsvm_dataset(config.input_path, config)
        valid = None
        if config.validation_path:
            with log.timed("read_validation_data", format=fmt):
                valid = _read_libsvm_dataset(
                    config.validation_path, config,
                    n_features=train.feature_dim(
                        next(iter(train.features))),
                )
    else:
        shards = sorted({c.feature_shard for c in config.coordinates})
        entity_keys = sorted({c.entity_key for c in config.coordinates
                              if c.entity_key})
        with log.timed("prepare_feature_maps"):
            if config.index_dir:
                feature_maps, entity_maps = load_index_maps(config.index_dir)
            else:
                feature_maps, entity_maps = build_index_maps(
                    config.input_path, shards, entity_keys
                )
        dense = tuple(config.dense_feature_shards)
        with log.timed("read_training_data", format=fmt):
            # Training extends the entity maps with ids the prebuilt
            # maps miss; the extended maps are what gets persisted.
            train = read_game_dataset(
                config.input_path, feature_maps, entity_maps,
                dense_shards=dense, extend_entity_maps=True,
            )
        valid = None
        if config.validation_path:
            with log.timed("read_validation_data", format=fmt):
                valid = read_game_dataset(
                    config.validation_path, feature_maps, entity_maps,
                    dense_shards=dense,
                )

    if valid is None and config.validation_fraction > 0.0:
        rng = np.random.default_rng(config.seed)
        perm = rng.permutation(train.n)
        n_valid = int(round(train.n * config.validation_fraction))
        valid = train.take(perm[:n_valid])
        train = train.take(perm[n_valid:])
        log.event("validation_split", n_train=train.n, n_valid=valid.n)

    return train, valid, feature_maps, entity_maps


def _save_result(result: FitResult, estimator: GameEstimator,
                 model_dir: str) -> dict:
    save_game_model(result.model, estimator.task, model_dir)
    return {
        "model_dir": model_dir,
        "reg_weights": result.reg_weights,
        "evaluations": {ev.value: v for ev, v in result.evaluations.items()},
        # Per-CD-iteration validation trace (reference per-sweep
        # evaluator logging); [] when trained without validation data.
        "validation_history": [
            {str(getattr(ev, "value", ev)): float(v)
             for ev, v in entry.items()} if isinstance(entry, dict)
            else float(entry)
            for entry in result.validation_history
        ],
    }


def distributed_init_from_env() -> None:
    """Join the JAX coordination service before first backend use
    (multi-host scale-out, SURVEY §7 stage 9).  Coordinator address /
    process count / index come from JAX_COORDINATOR_ADDRESS /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID (mapped here — JAX only
    auto-detects managed clusters like TPU pods/SLURM).  Idempotent so
    a caller-initialized process doesn't crash."""
    import jax

    # jax >= 0.5 has jax.distributed.is_initialized(); older builds
    # expose the same fact as global_state.client.
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is None:
        from jax._src import distributed as _dist

        def is_init():
            return _dist.global_state.client is not None
    if is_init():
        return
    from photon_ml_tpu.config import read_env

    kw = {}
    if read_env("JAX_COORDINATOR_ADDRESS"):
        kw["coordinator_address"] = read_env("JAX_COORDINATOR_ADDRESS")
    if read_env("JAX_NUM_PROCESSES"):
        kw["num_processes"] = int(read_env("JAX_NUM_PROCESSES"))
    if read_env("JAX_PROCESS_ID"):
        kw["process_id"] = int(read_env("JAX_PROCESS_ID"))
    jax.distributed.initialize(**kw)


def run(config: TrainingConfig, log: RunLogger | None = None) -> dict:
    """Full training pipeline; returns the written summary dict."""
    config.validate()
    # Warm path first: the persistent compilation cache must be wired
    # before any jit compiles (photon_ml_tpu.cache; falls back to
    # $PHOTON_ML_TPU_COMPILE_CACHE, no-op when neither is set).
    from photon_ml_tpu.cache import enable_compilation_cache

    enable_compilation_cache(config.compilation_cache_dir)
    if config.distributed_init:
        distributed_init_from_env()
    # Multi-host streaming (ISSUE 16): join the fleet if this process
    # was launched as one host of a sharded-streaming run (initialized
    # jax.distributed runtime → psum transport; PHOTON_FLEET_* env trio
    # → local tcp transport).  Each host then writes its OWN output
    # tree (run_log, summary, models, telemetry) under a host_NNN/
    # subdir — `telemetry fleet-report` joins the per-host logs into
    # the aggregated fleet view.
    from photon_ml_tpu.parallel import fleet

    fctx = fleet.initialize_from_env()
    if fctx is not None and fctx.is_fleet:
        config.output_dir = fleet.host_dir(config.output_dir, fctx)
    os.makedirs(config.output_dir, exist_ok=True)
    from photon_ml_tpu import telemetry
    from photon_ml_tpu.telemetry import monitor as _mon

    # Context-managed logger lifecycle (ISSUE 7 satellite: the handle
    # used to leak on paths that bypassed close); the telemetry session
    # shares the logger so spans/heartbeats land in the same JSONL the
    # report CLI reads.  A RESUMED run appends: the stitched log (first
    # run's torn tail + the resumed run's events) is the forensic
    # record `telemetry report` reconciles segment by segment.
    # Cadence flushing (ISSUE 10): a driver log plausibly has a live
    # consumer (`telemetry watch`, kill forensics), so it trades the
    # per-line flush syscall for a bounded staleness window.
    # The monitor spans the WHOLE pipeline (ETL phases included), not
    # just the fit — the estimator's own maybe_monitor nests as a
    # no-op under this one.
    with (log or RunLogger(os.path.join(config.output_dir,
                                        "run_log.jsonl"),
                           mode=("a" if config.resume else "w"),
                           header=True,
                           run_info={"driver": "game_training",
                                     "telemetry": config.telemetry,
                                     "resume": config.resume,
                                     **({"fleet_host": fctx.host_id,
                                         "fleet_hosts": fctx.n_hosts,
                                         "fleet_transport": fctx.transport}
                                        if fctx is not None
                                        and fctx.is_fleet else {})},
                           flush_every_s=DEFAULT_FLUSH_EVERY_S)
          ) as log, \
            telemetry.maybe_session(
                config.telemetry,
                config.telemetry_dir or config.output_dir,
                run_logger=log), \
            _mon.maybe_monitor(
                config.monitor == "on", run_logger=log,
                status_port=config.status_port,
                every_s=config.monitor_every_s):
        return _run(config, log)


def _run(config: TrainingConfig, log: RunLogger) -> dict:
    log.event("config", config=json.loads(config_to_json(config)))

    train, valid, feature_maps, entity_maps = prepare_data(config, log)
    log.event("datasets", n_train=train.n,
              n_valid=(valid.n if valid is not None else 0))

    estimator = GameEstimator(config)
    if config.tuning is not None:
        if valid is None:
            raise ValueError(
                "hyperparameter tuning needs validation data "
                "(validation_path or validation_fraction)")
        with log.timed("fit", profile_dir=config.profile_dir,
                       mode="tuning", trials=config.tuning.n_trials):
            results = estimator.fit_tuned(train, valid, run_logger=log)
    else:
        with log.timed("fit", profile_dir=config.profile_dir):
            results = estimator.fit(train, validation=valid, run_logger=log)
    best = estimator.best(results)

    for i, r in enumerate(results):
        log.event("grid_result", index=i, reg_weights=r.reg_weights,
                  evaluations={ev.value: v
                               for ev, v in r.evaluations.items()},
                  best=(r is best))

    # Identity, not ==: FitResult equality would recurse into jax arrays.
    summary = {"models": [],
               "best_index": next(i for i, r in enumerate(results)
                                  if r is best)}
    with log.timed("save_models", mode=config.model_output_mode):
        if config.model_output_mode == "ALL":
            for i, r in enumerate(results):
                summary["models"].append(_save_result(
                    r, estimator,
                    os.path.join(config.output_dir, f"model_{i}")))
        else:  # BEST (EXPLICIT reduces to BEST without a tuning run)
            summary["models"].append(_save_result(
                best, estimator, os.path.join(config.output_dir, "model")))
        if feature_maps is not None:
            save_index_maps(os.path.join(config.output_dir, "index_maps"),
                            feature_maps, entity_maps)

    with open(os.path.join(config.output_dir, "summary.json"), "w") as f:
        json.dump(summary, f, indent=2)
    with open(os.path.join(config.output_dir, "config.json"), "w") as f:
        f.write(config_to_json(config))
    log.event("done", best_index=summary["best_index"])
    return summary


def main(argv: list[str] | None = None) -> dict:
    parser = argparse.ArgumentParser(
        description="photon-ml-tpu GAME training driver"
    )
    parser.add_argument("--config", required=True,
                        help="training config JSON file")
    parser.add_argument("--output-dir", default=None,
                        help="override config output_dir")
    parser.add_argument("--spill-dir", default=None,
                        help="override config spill_dir: out-of-core "
                             "chunk store directory (default also "
                             "$PHOTON_ML_TPU_SPILL_DIR)")
    parser.add_argument("--host-max-resident", type=int, default=None,
                        help="override config host_max_resident: "
                             "decoded chunks kept live in host RAM "
                             "when spilling")
    parser.add_argument("--prefetch-depth", type=int, default=None,
                        help="override config prefetch_depth: chunks "
                             "prefetched disk->host->device ahead of "
                             "compute (0 disables the thread)")
    parser.add_argument("--re-chunk-entities", type=int, default=None,
                        help="override config re_chunk_entities: "
                             "out-of-core random-effect training — "
                             "entities per streamed chunk per size "
                             "bucket (requires a spill dir)")
    parser.add_argument("--re-retirement", choices=("on", "off"),
                        default=None,
                        help="override config re_retirement: freeze "
                             "converged entities between CD sweeps "
                             "(streamed random effects only)")
    parser.add_argument("--cd-fused", choices=("on", "off"),
                        default=None,
                        help="override config cd_fused: one streamed "
                             "store pass per CD cycle accumulates every "
                             "coordinate's statistics (Jacobi solves "
                             "against cycle-start offsets); requires "
                             "chunk_rows and smooth regularization")
    parser.add_argument("--telemetry", choices=("off", "metrics", "trace"),
                        default=None,
                        help="override config telemetry: pipeline "
                             "spans/metrics (metrics) + Chrome "
                             "trace.json export (trace); analyze with "
                             "python -m photon_ml_tpu.telemetry report")
    parser.add_argument("--telemetry-dir", default=None,
                        help="override config telemetry_dir (default: "
                             "the output dir)")
    parser.add_argument("--monitor", choices=("off", "on"),
                        default=None,
                        help="override config monitor: live progress/"
                             "ETA snapshots + online anomaly alerts in "
                             "the run log; follow with python -m "
                             "photon_ml_tpu.telemetry watch "
                             "<run_log.jsonl>")
    parser.add_argument("--monitor-every-s", type=float, default=None,
                        help="override config monitor_every_s: "
                             "snapshot/alert cadence in seconds")
    parser.add_argument("--status-port", type=int, default=None,
                        help="serve GET /status (JSON) and /metrics "
                             "(Prometheus text) from a localhost "
                             "thread on this port (0 = ephemeral, "
                             "logged as a status_server event); "
                             "implies --monitor on")
    parser.add_argument("--checkpoint-dir", default=None,
                        help="override config checkpoint_dir: "
                             "reliability checkpoints (CD sweep state, "
                             "mid-solve solver state) land here")
    parser.add_argument("--resume", action="store_true", default=None,
                        help="resume from the most advanced checkpoint "
                             "in checkpoint_dir (run log appends; "
                             "analyze the stitched log with "
                             "python -m photon_ml_tpu.telemetry report)")
    parser.add_argument("--checkpoint-every-sweeps", type=int,
                        default=None,
                        help="override config checkpoint_every_sweeps: "
                             "CD sweep-boundary snapshot cadence")
    parser.add_argument("--checkpoint-every-solver-iters", type=int,
                        default=None,
                        help="override config "
                             "checkpoint_every_solver_iters: streaming-"
                             "solver mid-solve snapshot cadence (0 = "
                             "sweep boundaries only)")
    args = parser.parse_args(argv)
    config = load_training_config(args.config)
    if args.output_dir:
        config.output_dir = args.output_dir
    if args.spill_dir is not None:
        config.spill_dir = args.spill_dir
    if args.host_max_resident is not None:
        config.host_max_resident = args.host_max_resident
    if args.prefetch_depth is not None:
        config.prefetch_depth = args.prefetch_depth
    if args.re_chunk_entities is not None:
        config.re_chunk_entities = args.re_chunk_entities
    if args.re_retirement is not None:
        config.re_retirement = args.re_retirement == "on"
    if args.cd_fused is not None:
        config.cd_fused = args.cd_fused == "on"
    if args.telemetry is not None:
        config.telemetry = args.telemetry
    if args.telemetry_dir is not None:
        config.telemetry_dir = args.telemetry_dir
    if args.monitor is not None:
        config.monitor = args.monitor
    if args.monitor_every_s is not None:
        config.monitor_every_s = args.monitor_every_s
    if args.status_port is not None:
        config.status_port = args.status_port
    if args.checkpoint_dir is not None:
        config.checkpoint_dir = args.checkpoint_dir
    if args.resume is not None:
        config.resume = args.resume
    if args.checkpoint_every_sweeps is not None:
        config.checkpoint_every_sweeps = args.checkpoint_every_sweeps
    if args.checkpoint_every_solver_iters is not None:
        config.checkpoint_every_solver_iters = (
            args.checkpoint_every_solver_iters)
    # Re-validate with the overrides applied (the spill/streamed-RE
    # cross-field rules must hold for the effective config).
    config.validate()
    return run(config)


if __name__ == "__main__":
    main()
