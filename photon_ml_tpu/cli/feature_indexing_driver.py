"""Feature indexing driver: scan data, build + persist index maps.

Reference counterpart: ``FeatureIndexingDriver``
(photon-client [expected path, mount unavailable — see SURVEY.md
§2.8/§3.4]): a dedicated Spark job that collects distinct ``(name,
term)`` feature keys per shard and writes one PalDB store per (shard,
partition) for executors to mmap.

Here: one host pass over the JSONL records → deterministic sorted-order
JSON maps per feature shard and per entity key (see
``photon_ml_tpu.io.index_map``).  Pre-building maps lets training
(``index_dir`` config field) and scoring skip the scan and guarantees
train/score index agreement across datasets.

Usage::

    python -m photon_ml_tpu.cli.feature_indexing_driver \
        --input data.jsonl --output-dir maps/ [--shards global user_re]
"""

from __future__ import annotations

import argparse

from photon_ml_tpu.io.dataset import build_index_maps
from photon_ml_tpu.io.index_map import save_index_maps
from photon_ml_tpu.utils.run_log import RunLogger


def run(input_path: str, output_dir: str,
        shards: list[str] | None = None,
        entity_keys: list[str] | None = None,
        log: RunLogger | None = None,
        telemetry_mode: str = "off",
        monitor: str = "off",
        status_port: int | None = None) -> dict:
    # Indexing itself is host-only, but wire the compilation cache
    # like the other drivers so $PHOTON_ML_TPU_COMPILE_CACHE covers any
    # jax use behind the I/O layer uniformly.
    from photon_ml_tpu import telemetry
    from photon_ml_tpu.cache import enable_compilation_cache
    from photon_ml_tpu.telemetry import monitor as _mon

    enable_compilation_cache()
    # Context-managed logger + optional telemetry session (the driver
    # knob discipline of the other two drivers): the scan phase becomes
    # a span and the summary/trace land under the output dir.  The
    # monitor/status knobs match too (ISSUE 10) — a large scan is a
    # silent single phase without them.
    with (log or RunLogger()) as log, \
            telemetry.maybe_session(telemetry_mode, output_dir,
                                    run_logger=log), \
            _mon.maybe_monitor(monitor == "on", run_logger=log,
                               status_port=status_port):
        with log.timed("build_index_maps", input=input_path):
            feature_maps, entity_maps = build_index_maps(
                input_path, shards, entity_keys
            )
        save_index_maps(output_dir, feature_maps, entity_maps)
        sizes = {
            "features": {s: len(m) for s, m in feature_maps.items()},
            "entities": {k: len(m) for k, m in entity_maps.items()},
        }
        log.event("index_maps_written", output_dir=output_dir, **sizes)
        return sizes


def main(argv: list[str] | None = None) -> dict:
    parser = argparse.ArgumentParser(
        description="photon-ml-tpu feature indexing driver"
    )
    parser.add_argument("--input", required=True, help="JSONL data file")
    parser.add_argument("--output-dir", required=True)
    parser.add_argument("--shards", nargs="*", default=None,
                        help="feature shards to index (default: all)")
    parser.add_argument("--entity-keys", nargs="*", default=None,
                        help="entity id keys to index (default: all)")
    parser.add_argument("--telemetry",
                        choices=("off", "metrics", "trace"),
                        default="off",
                        help="pipeline telemetry for the scan phase "
                             "(summary/trace land in --output-dir)")
    parser.add_argument("--monitor", choices=("off", "on"),
                        default="off",
                        help="live progress snapshots + online alerts "
                             "in the run log (ISSUE 10)")
    parser.add_argument("--status-port", type=int, default=None,
                        help="serve GET /status + /metrics from a "
                             "localhost thread on this port (0 = "
                             "ephemeral); implies --monitor on")
    args = parser.parse_args(argv)
    return run(args.input, args.output_dir, args.shards,
               args.entity_keys, telemetry_mode=args.telemetry,
               monitor=args.monitor, status_port=args.status_port)


if __name__ == "__main__":
    main()
