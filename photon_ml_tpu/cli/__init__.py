"""Drivers / CLI entry points (reference photon-client layer, SURVEY §2.8).

- ``game_training_driver`` — train GAME/GLM models from a config file
  (the legacy single-GLM driver is its degenerate one-coordinate case);
- ``game_scoring_driver`` — batch-score data with a saved model;
- ``feature_indexing_driver`` — build (name, term) → index maps.
"""
