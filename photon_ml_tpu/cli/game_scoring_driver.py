"""GAME scoring driver: saved model + data → scores (+ evaluation).

Reference counterpart: ``GameScoringDriver``
(photon-client ``com.linkedin.photon.ml.cli.game.scoring`` [expected
path, mount unavailable — see SURVEY.md §2.8/§3.2]): load model Avro +
data, ``GameTransformer.transform``, write ``ScoringResultAvro``,
optionally evaluate against true labels.

Usage::

    python -m photon_ml_tpu.cli.game_scoring_driver --config score.json

Output is an ``.npz`` with raw margins (``scores``), mean-space
predictions (``predictions`` — sigmoid/identity/exp per task), and the
input ``labels`` — the same fields ``ScoringResultAvro`` carries —
plus ``evaluation.json`` next to it when evaluators are configured.
An ``output_path`` ending in ``.avro`` writes reference-parity
``ScoringResultAvro`` records instead.

Two execution paths (ISSUE 4):

- ``score_chunk_rows`` unset: the resident per-coordinate
  ``GameTransformer.transform`` (validation-sized data).  The mean
  function is applied chunk-wise and Avro output is written in
  per-block batches either way — no full-array device round trip, no
  per-row Python encode loop.
- ``score_chunk_rows`` set: the streaming fused pipeline
  (``estimators.streaming_scorer``) — one pass in fixed-shape chunks,
  one fused device program per chunk, overlapped disk→host→device
  prefetch (``spill_dir``/``host_max_resident``/``prefetch_depth``),
  sinks and evaluators fed chunk-wise so nothing ``[n]``-sized stays
  resident.
"""

from __future__ import annotations

import argparse
import json
import os

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.config import ScoringConfig, load_scoring_config
from photon_ml_tpu.estimators.game_transformer import GameTransformer
from photon_ml_tpu.evaluation import evaluate
from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.io.dataset import detect_format, read_game_dataset
from photon_ml_tpu.io.index_map import load_index_maps
from photon_ml_tpu.io.libsvm import read_libsvm
from photon_ml_tpu.io.model_io import load_game_model
from photon_ml_tpu.models.game import FixedEffectModel, RandomEffectModel
from photon_ml_tpu.utils.run_log import DEFAULT_FLUSH_EVERY_S, RunLogger

# Chunk size for the resident path's chunk-wise mean application — the
# device sees [MEAN_CHUNK] slices, never the full margins array.
_MEAN_CHUNK = 1 << 20


def _read_data(config: ScoringConfig, model, log: RunLogger) -> GameDataset:
    fmt = detect_format(config.input_path, config.input_format)
    if fmt == "libsvm":
        fixed = [m for m in model.models.values()
                 if isinstance(m, FixedEffectModel)]
        if len(model.models) != 1 or not fixed:
            raise ValueError("LIBSVM scoring needs a single fixed-effect "
                             "model; use JSONL records for GAME models")
        shard = fixed[0].feature_shard
        # Model width fixes the feature space (minus the intercept column
        # the estimator appended at training time).
        dim = len(np.asarray(fixed[0].coefficients.means))
        if fixed[0].intercept:
            dim -= 1
        with log.timed("read_scoring_data", format=fmt):
            rows, labels, _ = read_libsvm(config.input_path, n_features=dim)
        return GameDataset(labels=labels, features={shard: rows},
                           entity_ids={}, feature_dims={shard: dim})

    index_dir = config.index_dir or os.path.join(
        os.path.dirname(os.path.abspath(config.model_dir)), "index_maps")
    with log.timed("prepare_feature_maps"):
        feature_maps, entity_maps = load_index_maps(index_dir)
    # Non-projected random effects score with a dense per-entity shard;
    # the model knows which those are — no config repetition required.
    dense = set(config.dense_feature_shards)
    dense.update(
        m.feature_shard for m in model.models.values()
        if isinstance(m, RandomEffectModel) and m.projection is None
    )
    with log.timed("read_scoring_data", format=fmt):
        return read_game_dataset(
            config.input_path, feature_maps, entity_maps,
            dense_shards=tuple(dense),
        )


def _mean_chunked(task, margins: np.ndarray) -> np.ndarray:
    """Mean-space predictions, applied device-chunk-wise (ISSUE 4
    satellite: the full-margins ``device_put`` round trip served only
    to evaluate an elementwise function)."""
    out = np.empty(len(margins), np.float32)
    for lo in range(0, len(margins), _MEAN_CHUNK):
        hi = min(lo + _MEAN_CHUNK, len(margins))
        out[lo:hi] = np.asarray(task.loss.mean(jnp.asarray(margins[lo:hi])))
    return out


def _make_sinks(config: ScoringConfig, n: int, entity_keys) -> list:
    if config.output_path.endswith(".avro"):
        from photon_ml_tpu.io.score_sink import AvroScoreSink

        return [AvroScoreSink(config.output_path,
                              ids_keys=tuple(entity_keys))]
    from photon_ml_tpu.io.score_sink import NpzScoreSink

    # np.savez appends ".npz" to extensionless paths; the streamed sink
    # must land on the same file name as the resident path.
    path = config.output_path
    if not path.endswith(".npz"):
        path += ".npz"
    return [NpzScoreSink(path, n)]


def run(config: ScoringConfig, log: RunLogger | None = None) -> dict:
    # Wire the persistent compilation cache before the scoring programs
    # compile (the 1037 s sweep compile is once per program shape).
    from photon_ml_tpu.cache import enable_compilation_cache

    enable_compilation_cache(config.compilation_cache_dir)
    config.validate()
    out_dir = os.path.dirname(os.path.abspath(config.output_path))
    os.makedirs(out_dir, exist_ok=True)
    from photon_ml_tpu import telemetry
    from photon_ml_tpu.telemetry import monitor as _mon

    # Context-managed logger lifecycle + shared telemetry session (see
    # the training driver): spans/heartbeats land in scoring_log.jsonl,
    # trace.json (telemetry=trace) in telemetry_dir.  Cadence flushing
    # + the live monitor (ISSUE 10): `telemetry watch` follows the
    # scoring log while the pass runs.
    with (log or RunLogger(os.path.join(out_dir,
                                        "scoring_log.jsonl"),
                           run_info={"driver": "game_scoring",
                                     "telemetry": config.telemetry},
                           flush_every_s=DEFAULT_FLUSH_EVERY_S)
          ) as log, \
            telemetry.maybe_session(
                config.telemetry, config.telemetry_dir or out_dir,
                run_logger=log), \
            _mon.maybe_monitor(
                config.monitor == "on", run_logger=log,
                status_port=config.status_port,
                every_s=config.monitor_every_s):
        return _run(config, log)


def _run_streamed(config: ScoringConfig, model, task, data,
                  log: RunLogger) -> dict:
    from photon_ml_tpu.data.chunk_store import resolve_spill_dir
    from photon_ml_tpu.estimators.streaming_scorer import (
        StreamingGameScorer,
    )
    from photon_ml_tpu.evaluation.streaming import make_streaming_evaluator

    scorer = StreamingGameScorer(
        model=model, task=task,
        chunk_rows=config.score_chunk_rows,
        spill_dir=resolve_spill_dir(config.spill_dir),
        host_max_resident=config.host_max_resident,
        prefetch_depth=config.prefetch_depth)
    sinks = _make_sinks(config, data.n, data.entity_ids)
    evaluators = [make_streaming_evaluator(ev)
                  for ev in config.evaluators]
    with log.timed("transform_streamed",
                   chunk_rows=config.score_chunk_rows):
        result = scorer.score(data, sinks=sinks, evaluators=evaluators)
    log.event("stream_stats",
              **{k: v for k, v in result.items()
                 if k not in ("evaluation",)})
    return result["evaluation"]


def _run(config: ScoringConfig, log: RunLogger) -> dict:
    out_dir = os.path.dirname(os.path.abspath(config.output_path))
    with log.timed("load_model"):
        model, task = load_game_model(config.model_dir)
    data = _read_data(config, model, log)
    log.event("dataset", n=data.n)

    if config.score_chunk_rows is not None:
        evaluation = _run_streamed(config, model, task, data, log)
    else:
        transformer = GameTransformer(model=model, task=task)
        with log.timed("transform"):
            margins = transformer.transform(data)
        predictions = _mean_chunked(task, margins)

        if config.output_path.endswith(".avro"):
            # Reference-parity output: ScoringResultAvro records,
            # written one container block per chunk (the per-row
            # dict-building Python loop is gone — ISSUE 4).  Same sink
            # wiring as the streamed path (_make_sinks), so the two
            # paths cannot diverge.
            sink = _make_sinks(config, data.n, data.entity_ids)[0]
            try:
                for lo in range(0, data.n, _MEAN_CHUNK):
                    hi = min(lo + _MEAN_CHUNK, data.n)
                    sink.write(lo, hi, margins[lo:hi],
                               predictions[lo:hi], data.labels[lo:hi],
                               ids={k: v[lo:hi]
                                    for k, v in data.entity_ids.items()})
                sink.close()
            except BaseException:
                sink.abort()
                raise
        else:
            np.savez(config.output_path, scores=margins,
                     predictions=predictions, labels=data.labels)

        evaluation = {}
        if config.evaluators:
            labels = jnp.asarray(data.labels.astype(np.float32))
            weights = jnp.asarray(data.weight_array())
            for ev in config.evaluators:
                scores = jnp.asarray(margins)
                if ev.value in ("RMSE", "SQUARED_LOSS"):
                    scores = jnp.asarray(predictions)
                evaluation[ev.value] = float(
                    evaluate(ev, scores, labels, weights))

    if config.evaluators:
        with open(os.path.join(out_dir, "evaluation.json"), "w") as f:
            json.dump(evaluation, f, indent=2)
        log.event("evaluation", **evaluation)

    log.event("done", output=config.output_path)
    return {"output_path": config.output_path, "n": int(data.n),
            "evaluation": evaluation}


def main(argv: list[str] | None = None) -> dict:
    parser = argparse.ArgumentParser(
        description="photon-ml-tpu GAME scoring driver"
    )
    parser.add_argument("--config", required=True,
                        help="scoring config JSON file")
    parser.add_argument("--score-chunk-rows", type=int, default=None,
                        help="override: chunk size for the streaming "
                             "fused scoring pipeline")
    parser.add_argument("--spill-dir", default=None,
                        help="override: disk tier for prepared score "
                             "chunks (default $PHOTON_ML_TPU_SPILL_DIR)")
    parser.add_argument("--host-max-resident", type=int, default=None,
                        help="override: LRU host window (chunks)")
    parser.add_argument("--prefetch-depth", type=int, default=None,
                        help="override: background prefetch depth "
                             "(0 = synchronous)")
    parser.add_argument("--telemetry", choices=("off", "metrics", "trace"),
                        default=None,
                        help="override config telemetry: pipeline "
                             "spans/metrics (metrics) + Chrome "
                             "trace.json export (trace); analyze with "
                             "python -m photon_ml_tpu.telemetry report")
    parser.add_argument("--telemetry-dir", default=None,
                        help="override config telemetry_dir (default: "
                             "the output file's directory)")
    parser.add_argument("--monitor", choices=("off", "on"),
                        default=None,
                        help="override config monitor: live progress/"
                             "ETA snapshots + online anomaly alerts; "
                             "follow with python -m photon_ml_tpu"
                             ".telemetry watch <scoring_log.jsonl>")
    parser.add_argument("--monitor-every-s", type=float, default=None,
                        dest="monitor_every_s",
                        help="override config monitor_every_s: "
                             "snapshot/alert cadence in seconds")
    parser.add_argument("--status-port", type=int, default=None,
                        dest="status_port",
                        help="serve GET /status + /metrics from a "
                             "localhost thread on this port (0 = "
                             "ephemeral); implies --monitor on")
    args = parser.parse_args(argv)
    config = load_scoring_config(args.config)
    for name in ("score_chunk_rows", "spill_dir", "host_max_resident",
                 "prefetch_depth", "telemetry", "telemetry_dir",
                 "monitor", "monitor_every_s", "status_port"):
        val = getattr(args, name)
        if val is not None:
            setattr(config, name, val)
    return run(config)   # run() re-validates (the overrides included)


if __name__ == "__main__":
    main()
