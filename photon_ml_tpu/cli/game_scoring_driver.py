"""GAME scoring driver: saved model + data → scores (+ evaluation).

Reference counterpart: ``GameScoringDriver``
(photon-client ``com.linkedin.photon.ml.cli.game.scoring`` [expected
path, mount unavailable — see SURVEY.md §2.8/§3.2]): load model Avro +
data, ``GameTransformer.transform``, write ``ScoringResultAvro``,
optionally evaluate against true labels.

Usage::

    python -m photon_ml_tpu.cli.game_scoring_driver --config score.json

Output is an ``.npz`` with raw margins (``scores``), mean-space
predictions (``predictions`` — sigmoid/identity/exp per task), and the
input ``labels`` — the same fields ``ScoringResultAvro`` carries —
plus ``evaluation.json`` next to it when evaluators are configured.
"""

from __future__ import annotations

import argparse
import json
import os

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.config import ScoringConfig, load_scoring_config
from photon_ml_tpu.estimators.game_transformer import GameTransformer
from photon_ml_tpu.evaluation import evaluate
from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.io.dataset import detect_format, read_game_dataset
from photon_ml_tpu.io.index_map import load_index_maps
from photon_ml_tpu.io.libsvm import read_libsvm
from photon_ml_tpu.io.model_io import load_game_model
from photon_ml_tpu.models.game import FixedEffectModel, RandomEffectModel
from photon_ml_tpu.utils.run_log import RunLogger


def _read_data(config: ScoringConfig, model, log: RunLogger) -> GameDataset:
    fmt = detect_format(config.input_path, config.input_format)
    if fmt == "libsvm":
        fixed = [m for m in model.models.values()
                 if isinstance(m, FixedEffectModel)]
        if len(model.models) != 1 or not fixed:
            raise ValueError("LIBSVM scoring needs a single fixed-effect "
                             "model; use JSONL records for GAME models")
        shard = fixed[0].feature_shard
        # Model width fixes the feature space (minus the intercept column
        # the estimator appended at training time).
        dim = len(np.asarray(fixed[0].coefficients.means))
        if fixed[0].intercept:
            dim -= 1
        with log.timed("read_scoring_data", format=fmt):
            rows, labels, _ = read_libsvm(config.input_path, n_features=dim)
        return GameDataset(labels=labels, features={shard: rows},
                           entity_ids={}, feature_dims={shard: dim})

    index_dir = config.index_dir or os.path.join(
        os.path.dirname(os.path.abspath(config.model_dir)), "index_maps")
    with log.timed("prepare_feature_maps"):
        feature_maps, entity_maps = load_index_maps(index_dir)
    # Non-projected random effects score with a dense per-entity shard;
    # the model knows which those are — no config repetition required.
    dense = set(config.dense_feature_shards)
    dense.update(
        m.feature_shard for m in model.models.values()
        if isinstance(m, RandomEffectModel) and m.projection is None
    )
    with log.timed("read_scoring_data", format=fmt):
        return read_game_dataset(
            config.input_path, feature_maps, entity_maps,
            dense_shards=tuple(dense),
        )


def run(config: ScoringConfig, log: RunLogger | None = None) -> dict:
    # Wire the persistent compilation cache before the scoring programs
    # compile (the 1037 s sweep compile is once per program shape).
    from photon_ml_tpu.cache import enable_compilation_cache

    enable_compilation_cache(config.compilation_cache_dir)
    out_dir = os.path.dirname(os.path.abspath(config.output_path))
    os.makedirs(out_dir, exist_ok=True)
    if log is None:
        log = RunLogger(os.path.join(out_dir, "scoring_log.jsonl"))
    try:
        return _run(config, log)
    finally:
        log.close()


def _run(config: ScoringConfig, log: RunLogger) -> dict:
    out_dir = os.path.dirname(os.path.abspath(config.output_path))
    with log.timed("load_model"):
        model, task = load_game_model(config.model_dir)
    data = _read_data(config, model, log)
    log.event("dataset", n=data.n)

    transformer = GameTransformer(model=model, task=task)
    with log.timed("transform"):
        margins = transformer.transform(data)
    predictions = np.asarray(task.loss.mean(jnp.asarray(margins)))

    if config.output_path.endswith(".avro"):
        # Reference-parity output: ScoringResultAvro records.
        from photon_ml_tpu.io.avro import write_container
        from photon_ml_tpu.io.avro_schemas import SCORING_RESULT_SCHEMA

        write_container(
            config.output_path,
            SCORING_RESULT_SCHEMA,
            ({"uid": i,
              "predictionScore": float(predictions[i]),
              "label": float(data.labels[i]),
              "ids": {k: str(int(col[i]))
                      for k, col in data.entity_ids.items()}}
             for i in range(data.n)),
        )
    else:
        np.savez(config.output_path, scores=margins,
                 predictions=predictions, labels=data.labels)

    evaluation = {}
    if config.evaluators:
        labels = jnp.asarray(data.labels.astype(np.float32))
        weights = jnp.asarray(data.weight_array())
        for ev in config.evaluators:
            scores = jnp.asarray(margins)
            if ev.value in ("RMSE", "SQUARED_LOSS"):
                scores = jnp.asarray(predictions)
            evaluation[ev.value] = float(
                evaluate(ev, scores, labels, weights))
        with open(os.path.join(out_dir, "evaluation.json"), "w") as f:
            json.dump(evaluation, f, indent=2)
        log.event("evaluation", **evaluation)

    log.event("done", output=config.output_path)
    return {"output_path": config.output_path, "n": int(data.n),
            "evaluation": evaluation}


def main(argv: list[str] | None = None) -> dict:
    parser = argparse.ArgumentParser(
        description="photon-ml-tpu GAME scoring driver"
    )
    parser.add_argument("--config", required=True,
                        help="scoring config JSON file")
    args = parser.parse_args(argv)
    return run(load_scoring_config(args.config))


if __name__ == "__main__":
    main()
