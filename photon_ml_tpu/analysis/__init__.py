"""photon-lint: static AST invariant checkers + runtime guard harness.

Rounds 6-10 earned their speedups by hand-enforcing invariants --
module-level jitted programs so sequential grid points stop recompiling
(PR 2), prefetch backpressure and store reader accounting so the async
pipeline cannot un-bound what the LRU window bounds (PR 3), host
float64 metric folds (PR 4) -- but nothing in the repo *checked* any of
it, so the multi-host streaming, fused-CD, and serving tiers queued in
ROADMAP items 1-3 (more threads, more compiles, more host<->device
traffic) could silently regress them.  "Understanding and Optimizing
the Performance of Distributed ML Applications on Apache Spark"
(PAPERS.md) documents exactly this failure mode at the reference
system's scale: the dominant costs were accidental serialization /
recompute patterns invisible until profiled.  This package encodes our
contracts twice:

- ``checkers``: AST-based static rules over the whole package
  (jit discipline, tracer hygiene, thread/lock discipline, accumulator
  dtype, env hygiene, slow-test markers), run by
  ``python -m photon_ml_tpu.analysis`` and enforced in tier-1 by
  ``tests/test_analysis.py::test_repo_clean``.
- ``guards``: runtime context managers (compile counting via
  ``jax.log_compiles``, ``jax.check_tracer_leaks``,
  ``jax.transfer_guard``) with budget assertions wired into the
  hot-path tests and ``bench.py --guards``.
"""

from photon_ml_tpu.analysis.checkers import (  # noqa: F401
    RULES,
    Violation,
    check_source,
    run_checks,
)
from photon_ml_tpu.analysis.guards import (  # noqa: F401
    count_compiles,
    no_implicit_transfers,
    tracer_leak_guard,
)
