"""Runtime guard harness: compile / transfer / tracer-leak budgets.

The static checkers prove the *code shape* keeps the rounds-6..10
contracts; these context managers prove the *runtime* does:

- ``count_compiles()``: every XLA compilation inside the scope,
  counted by listening to ``jax.log_compiles`` output (the
  "Compiling <name> with global shapes" records from
  ``jax._src.interpreters.pxla``).  The budget assertions in the
  hot-path tests pin them: a streaming L-BFGS sweep compiles the same
  fixed program set whether the data is 4 chunks or 24 (the chunk
  programs are shape-congruent -- PR 2/3's whole point), and a warm
  re-fit compiles ZERO new programs.
- ``no_implicit_transfers()``: ``jax.transfer_guard`` over the scope.
  Planned transfers stay allowed -- chunk placement is an explicit
  ``jax.device_put`` and result harvest an explicit
  ``jax.device_get`` -- so any *implicit* host<->device copy inside a
  per-chunk loop is a pipeline bug (an un-planned sync that the
  prefetch overlap cannot hide).  NOTE: the CPU backend is exempt by
  construction (host == device, jax raises no transfer events), so
  the guard is load-bearing on TPU/GPU and structurally a no-op under
  ``JAX_PLATFORMS=cpu`` -- tests wire it anyway so accelerator runs
  inherit the contract.
- ``tracer_leak_guard()``: ``jax.check_tracer_leaks`` over the scope;
  a traced value escaping a jitted program (the classic closure leak)
  becomes a loud error instead of a silent retrace anchor.

All three nest and are reentrant-safe in the way the tests use them
(one scope at a time per process; the compile listener is additive, so
nested ``count_compiles`` scopes each see the inner compilations).
"""

from __future__ import annotations

import logging
import re
from contextlib import contextmanager

# The pxla compile record: "Compiling <name> with global shapes and
# types ...".  Keyed on the leading verb so tracing/lowering records
# ("Finished tracing ...", "Finished XLA compilation ...") are not
# double-counted.
_COMPILE_RE = re.compile(r"^Compiling ([^\s]+)")


class CompileLog:
    """Collected compile events for one ``count_compiles`` scope."""

    def __init__(self):
        self.programs: list[str] = []

    @property
    def count(self) -> int:
        return len(self.programs)

    def named(self, *names: str) -> list[str]:
        """Events whose program name matches any of ``names``
        (budget assertions usually pin the interesting programs and
        ignore the eager convert/broadcast helpers)."""
        return [p for p in self.programs if p in names]

    def __repr__(self) -> str:
        return f"CompileLog(count={self.count}, programs={self.programs})"


class _CompileHandler(logging.Handler):
    def __init__(self, log: CompileLog):
        super().__init__(level=logging.DEBUG)
        self._log = log

    def emit(self, record: logging.LogRecord) -> None:
        try:
            m = _COMPILE_RE.match(record.getMessage())
        except Exception:  # photon-lint: disable=swallowed-exception (a guard must never break the run)
            return
        if m:
            # list.append is atomic under the GIL; compile records can
            # arrive from dispatch on any thread.
            self._log.programs.append(m.group(1))


@contextmanager
def count_compiles():
    """Count XLA compilations in the scope (yields a ``CompileLog``).

    Listens on the ``jax`` logger with ``jax.log_compiles`` enabled;
    the records propagate from ``jax._src.interpreters.pxla``, one per
    compiled program, named after the jitted callable -- so budget
    tests can assert both totals and per-program presence."""
    import jax

    log = CompileLog()
    handler = _CompileHandler(log)
    jax_logger = logging.getLogger("jax")
    old_level = jax_logger.level
    jax_logger.addHandler(handler)
    # The handler must SEE the records: compile records are emitted at
    # WARNING by log_compiles, and the jax logger is normally NOTSET —
    # its EFFECTIVE level comes from the root logger, so an app that
    # configured root above WARNING would silently drop every record
    # (and make all zero-compile budget assertions pass vacuously).
    if jax_logger.getEffectiveLevel() > logging.WARNING:
        jax_logger.setLevel(logging.WARNING)
    try:
        with jax.log_compiles():
            yield log
    finally:
        jax_logger.removeHandler(handler)
        jax_logger.setLevel(old_level)


@contextmanager
def no_implicit_transfers(level: str = "disallow"):
    """Forbid (or ``level="log"``: report) implicit host<->device
    transfers in the scope.  Explicit ``jax.device_put`` /
    ``jax.device_get`` -- the planned chunk placement and harvest --
    stay allowed; anything else inside a per-chunk loop is an
    unplanned sync.  No-op on the CPU backend (host == device)."""
    import jax

    with jax.transfer_guard(level):
        yield


@contextmanager
def tracer_leak_guard():
    """Raise on tracers escaping a jitted scope
    (``jax.check_tracer_leaks``)."""
    import jax

    with jax.check_tracer_leaks():
        yield
