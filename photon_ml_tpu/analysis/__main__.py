"""photon-lint CLI: ``python -m photon_ml_tpu.analysis``.

Runs the AST checker suite over the package (and the recorded-duration
test audit) and exits 0 (clean) / 1 (violations), printing one
``path:line rule-id message`` line per violation and -- the repo's
CLI contract -- a final machine-readable JSON line either way.

``--format github`` emits GitHub Actions ``::error`` annotations
instead of the plain lines (the JSON tail line is unchanged), so a CI
step can surface violations inline on the PR diff.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

from photon_ml_tpu.analysis.checkers import RULES, run_checks


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m photon_ml_tpu.analysis",
        description=__doc__.split("\n")[0])
    p.add_argument("paths", nargs="*",
                   help="specific files to check (default: the whole "
                        "photon_ml_tpu package + the slow-test audit)")
    p.add_argument("--root", default=None,
                   help="repo root (default: the package's parent)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule ids to run "
                        f"({'|'.join(RULES)}); default all")
    p.add_argument("--format", choices=("text", "github"),
                   default="text")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    if args.list_rules:
        for rule, desc in RULES.items():
            print(f"{rule}: {desc}")
        print(json.dumps({"rules": sorted(RULES)}))
        return 0

    root = args.root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    rules = (set(r for r in args.rules.split(",") if r)
             if args.rules else None)
    if rules:
        unknown = rules - set(RULES)
        if unknown:
            p.error(f"unknown rules {sorted(unknown)}; "
                    f"pick from {sorted(RULES)}")
    files = [os.path.abspath(f) for f in args.paths] or None

    violations, n_files = run_checks(root, rules=rules, files=files)
    for v in violations:
        # Repo-relative paths: GitHub ::error annotations only attach
        # to the PR diff with workspace-relative `file=` values, and
        # the text form reads better too.
        shown = dataclasses.replace(
            v, path=os.path.relpath(v.path, root))
        print(shown.github() if args.format == "github" else str(shown))

    per_rule: dict[str, int] = {}
    for v in violations:
        per_rule[v.rule] = per_rule.get(v.rule, 0) + 1
    print(json.dumps({
        "ok": not violations,
        "violations": len(violations),
        "files_checked": n_files,
        "rules_run": sorted(rules) if rules else sorted(RULES),
        "by_rule": per_rule,
    }))
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
