"""AST invariant checkers (photon-lint).

Each rule encodes a performance/correctness contract an earlier round
established by hand and a later tier could silently regress:

- ``jit-in-function``: no ``jax.jit`` / ``partial(jax.jit, ...)``
  constructed inside function bodies or loops.  A per-call jit wrapper
  owns a fresh executable cache, so every call re-traces and recompiles
  the identical program -- the exact recompile hazard PR 2 removed from
  the lambda-grid loop by hoisting per-chunk programs to module level.
  Jits must be module-level or memoized (an ``functools.lru_cache`` /
  ``functools.cache`` enclosing function is exempt).
- ``tracer-hygiene``: no ``np.*`` calls, ``float()``/``int()``/
  ``bool()``/``.item()`` casts, or ``if``-branching applied to values
  that flow from a jitted/vmapped function's array parameters
  (``static_argnums`` excluded).  Any of these forces a trace-time
  concretization error at best, a silent host round-trip at worst.
- ``unlocked-shared-write``: classes that spawn ``threading.Thread`` /
  ``ThreadPoolExecutor`` (or that own a lock) must mutate shared
  attributes under their lock or communicate via ``queue.Queue`` /
  ``threading.Event``.  Flags writes reachable from both the worker
  and the caller that are not lexically under a ``with self.<lock>:``.
- ``accumulator-dtype``: streaming metric/loss accumulators (classes
  with the ``update``/``result`` protocol) must fold on host in
  float64 -- accumulation expressions must not run through ``jnp``
  (device f32 folds) or explicit float32 casts.
- ``env-read``: no raw ``os.environ`` / ``os.getenv`` reads outside
  ``config.py``'s sanctioned registry (``config.read_env``) -- scatter
  env fallbacks are invisible configuration.
- ``swallowed-exception``: an ``except`` whose body only
  passes/continues/breaks/bare-returns — the failure is silently
  discarded
  (ISSUE 9: fault tolerance is only honest when every absorbed failure
  is reported, handled with a real fallback, or waived with a reason).
- ``eternal-wait``: in a thread-spawning class, a blocking wait with
  no timeout — ``queue.get()``, ``Event.wait()``, ``Thread.join()``,
  ``socket.recv()`` — can pin a thread forever when its peer dies
  (ISSUE 13: the serving tier's wedged-handler class of outage).
  Every cross-thread wait must be bounded, or waived with the reason
  the block is provably terminated (e.g. a close() sentinel).
- ``while-loop-carry-dtype``: a ``lax.while_loop`` body whose carry
  leaf changes dtype fails at trace time with an opaque
  body-function-output-mismatch error (ISSUE 17: an f64 cast — or a
  float literal folded into an int/bool carry — silently rewrites the
  leaf's dtype).  Flags mismatched-literal arithmetic on carry names
  inside while-body functions whose init dtype is statically inferable.
- ``slow-unmarked``: tests whose recorded tier-1 duration exceeds the
  threshold must carry ``@pytest.mark.slow`` so the tier-1 wall clock
  stops creeping (durations recorded once in
  ``tests/tier1_durations.json``; see PERF.md).

Waivers: a violation line may carry an inline waiver comment

    # photon-lint: disable=<rule>[,<rule>] (<reason>)

The reason is mandatory -- a waiver without one is ignored (and
reported), so every suppression documents why the contract does not
apply at that site.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize

# Test duration above which a test must be @pytest.mark.slow (seconds).
# Pinned at 10 s at introduction: the 5-10 s band holds ~30 more cases
# whose removal would take tier-1 below its seed pass-count floor;
# ratchet the threshold down as the fast tier grows (ISSUE 6 audit —
# the 15 functions over 10 s were marked, cutting ~266 s of tier-1
# wall clock; measurements in tests/tier1_durations.json).
SLOW_THRESHOLD_S = 10.0

# Recorded tier-1 durations (max over parametrizations, seconds),
# measured once per re-baseline -- see tests/tier1_durations.json.
DURATIONS_FILE = os.path.join("tests", "tier1_durations.json")

RULES = {
    "jit-in-function": (
        "jax.jit constructed inside a function body or loop "
        "(per-call recompile hazard; hoist to module level or memoize)"
    ),
    "tracer-hygiene": (
        "host-side numpy/cast/branch applied to a traced array value "
        "inside a jitted/vmapped function"
    ),
    "unlocked-shared-write": (
        "shared mutable attribute written without the owning lock in a "
        "thread-spawning class"
    ),
    "accumulator-dtype": (
        "streaming accumulator folds through jnp/float32 instead of "
        "host float64"
    ),
    "env-read": (
        "raw os.environ read outside config.py's sanctioned registry "
        "(use photon_ml_tpu.config.read_env)"
    ),
    "naked-clock": (
        "time.time() used in duration arithmetic; wall clock steps "
        "under NTP/suspend — use time.monotonic()/time.perf_counter()"
    ),
    "metric-name": (
        "telemetry counter/gauge/histogram registered under a name "
        "that is not a dotted lowercase identifier (namespace.metric)"
    ),
    "swallowed-exception": (
        "except handler silently discards the failure (pass/continue/"
        "break/bare return) without re-raising or logging — waiver "
        "with reason for deliberate best-effort sites"
    ),
    "eternal-wait": (
        "unbounded blocking wait (queue.get()/Event.wait()/"
        "Thread.join()/socket.recv() with no timeout) in a "
        "thread-spawning class — a dead peer pins the thread forever; "
        "bound it or waive with the termination argument"
    ),
    "collective-in-host-branch": (
        "psum/all_gather/... lexically inside a branch conditioned on "
        "the process identity (process_index()/host_id) — hosts that "
        "skip the branch never reach the collective and the fleet "
        "deadlocks at the barrier"
    ),
    "while-loop-carry-dtype": (
        "arithmetic on a lax.while_loop carry name whose literal "
        "operand changes the carry leaf's dtype (f64 cast, or a float "
        "literal on an int/bool carry) — the body/carry dtype mismatch "
        "fails at trace time with an opaque error"
    ),
    "slow-unmarked": (
        "test measured slower than the threshold lacks "
        "@pytest.mark.slow"
    ),
    "bad-waiver": (
        "photon-lint waiver without a (reason) — every suppression "
        "must say why the contract does not apply"
    ),
    "syntax-error": "file failed to parse",
}

_WAIVER_RE = re.compile(
    r"#\s*photon-lint:\s*disable=([\w,-]+)\s*(?:\((.*?)\))?")


def _comments(source: str):
    """(lineno, text, comment_only) for every real COMMENT token.

    ``comment_only`` is True when nothing but whitespace precedes the
    comment on its line.  Tokenization errors (the caller has already
    ast-parsed the file, so these are near-impossible) degrade to the
    comments seen so far."""
    out = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out.append((tok.start[0], tok.string,
                            tok.line[: tok.start[1]].strip() == ""))
    except (tokenize.TokenError, IndentationError):  # photon-lint: disable=swallowed-exception (degrade to the comments seen so far, documented above)
        pass
    return out


@dataclasses.dataclass(frozen=True)
class Violation:
    path: str
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line} {self.rule} {self.message}"

    def github(self) -> str:
        return (f"::error file={self.path},line={self.line},"
                f"title={self.rule}::{self.message}")


# ---------------------------------------------------------------------------
# Shared AST plumbing
# ---------------------------------------------------------------------------


def _parents(tree: ast.AST) -> dict:
    par: dict = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            par[child] = node
    return par


def _ancestors(node: ast.AST, par: dict):
    n = par.get(node)
    while n is not None:
        yield n
        n = par.get(n)


def _dotted(node: ast.AST) -> str | None:
    """``a.b.c`` attribute/name chain as a string, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def _is_jit_call(call: ast.Call) -> bool:
    """``jax.jit(...)`` or ``[functools.]partial(jax.jit, ...)``."""
    tgt = _dotted(call.func)
    if tgt in ("jax.jit", "jax.pmap"):
        return True
    if tgt in ("partial", "functools.partial") and call.args:
        return _dotted(call.args[0]) in ("jax.jit", "jax.pmap")
    return False


def _static_argnums(call: ast.Call) -> tuple[set[int], set[str]]:
    """Literal static_argnums / static_argnames from a jit call."""
    nums: set[int] = set()
    names: set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    nums.add(e.value)
        elif kw.arg == "static_argnames":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    names.add(e.value)
    return nums, names


class _FileContext:
    """One parsed source file + its waiver table."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.parents = _parents(self.tree)
        self.waivers: dict[int, set[str]] = {}
        self.bad_waivers: list[int] = []
        lines = source.splitlines()
        # Real COMMENT tokens only (tokenize): a waiver example quoted
        # inside a docstring/string literal must neither suppress the
        # next code line nor be reported as a bad waiver.
        for lineno, text, comment_only in _comments(source):
            m = _WAIVER_RE.search(text)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            reason = (m.group(2) or "").strip()
            if not reason:
                self.bad_waivers.append(lineno)
                continue
            self.waivers.setdefault(lineno, set()).update(rules)
            # A waiver on a comment-only line covers the next code
            # line (the inline form rarely fits the line limit).
            if comment_only:
                nxt = lineno + 1
                while nxt <= len(lines) and (
                        not lines[nxt - 1].strip()
                        or lines[nxt - 1].strip().startswith("#")):
                    nxt += 1
                if nxt <= len(lines):
                    self.waivers.setdefault(nxt, set()).update(rules)

    def waived(self, line: int, rule: str) -> bool:
        return rule in self.waivers.get(line, ())


# ---------------------------------------------------------------------------
# Rule: jit-in-function
# ---------------------------------------------------------------------------


_MEMO_DECORATORS = ("functools.lru_cache", "lru_cache", "functools.cache",
                    "cache")


def _is_memoized(fn: ast.AST) -> bool:
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    for dec in fn.decorator_list:
        d = dec.func if isinstance(dec, ast.Call) else dec
        if _dotted(d) in _MEMO_DECORATORS:
            return True
    return False


def check_jit_in_function(ctx: _FileContext):
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and _is_jit_call(node):
            enclosing = None
            in_loop = False
            for anc in _ancestors(node, ctx.parents):
                if isinstance(anc, (ast.For, ast.While)):
                    in_loop = True
                if isinstance(anc, (ast.FunctionDef,
                                    ast.AsyncFunctionDef, ast.Lambda)):
                    enclosing = anc
                    break
            if enclosing is None and not in_loop:
                continue
            if enclosing is not None and _is_memoized(enclosing):
                continue
            # A decorator expression evaluates at def time, which for a
            # module-level def is module scope -- exempt.
            parent = ctx.parents.get(node)
            if (isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node in parent.decorator_list
                    and parent is enclosing):
                continue
            where = ("a loop" if enclosing is None
                     else f"'{getattr(enclosing, 'name', '<lambda>')}'")
            yield Violation(
                ctx.path, node.lineno, "jit-in-function",
                f"jax.jit constructed inside {where}: every call "
                "re-traces and recompiles; hoist to module level or "
                "memoize (functools.lru_cache)")
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # @jax.jit on a def nested inside another function: the
            # wrapper (and its compile cache) is rebuilt per outer call.
            for anc in _ancestors(node, ctx.parents):
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda)):
                    if _is_memoized(anc):
                        break
                    for dec in node.decorator_list:
                        # Bare @jax.jit (an Attribute — _dotted returns
                        # None for Call nodes) or @partial(jax.jit, …).
                        if _dotted(dec) in ("jax.jit", "jax.pmap") or (
                                isinstance(dec, ast.Call)
                                and _is_jit_call(dec)):
                            yield Violation(
                                ctx.path, node.lineno, "jit-in-function",
                                f"@jax.jit on '{node.name}' nested "
                                f"inside '{anc.name}': the wrapper is "
                                "rebuilt (and recompiled) per outer "
                                "call")
                            break
                    break


# ---------------------------------------------------------------------------
# Rule: tracer-hygiene
# ---------------------------------------------------------------------------

_NP_ALIASES = ("np", "numpy")
_TRANSFORM_CALLS = ("jax.jit", "jax.vmap", "jax.pmap")


def _jit_targets(ctx: _FileContext):
    """(function node, static positions, static names) for every
    function this file jits/vmaps: decorated defs, and module-level
    ``name = jax.jit(fn_or_lambda, ...)`` assignments."""
    defs: dict[str, ast.AST] = {}
    lambdas: dict[str, ast.Lambda] = {}
    for node in ctx.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name) and isinstance(node.value,
                                                      ast.Lambda):
                lambdas[t.id] = node.value

    seen: set[int] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                tgt = _dotted(dec if not isinstance(dec, ast.Call)
                              else dec.func)
                if tgt in _TRANSFORM_CALLS:
                    if id(node) not in seen:
                        seen.add(id(node))
                        yield node, set(), set()
                elif isinstance(dec, ast.Call) and _is_jit_call(dec):
                    nums, names = _static_argnums(dec)
                    if id(node) not in seen:
                        seen.add(id(node))
                        yield node, nums, names
        elif isinstance(node, ast.Call) and (
                _dotted(node.func) in _TRANSFORM_CALLS) and node.args:
            fn = node.args[0]
            nums, names = _static_argnums(node)
            target = None
            if isinstance(fn, ast.Lambda):
                target = fn
            elif isinstance(fn, ast.Name):
                target = defs.get(fn.id) or lambdas.get(fn.id)
            if target is not None and id(target) not in seen:
                seen.add(id(target))
                yield target, nums, names


def _tainted_params(fn, static_nums: set[int],
                    static_names: set[str]) -> set[str]:
    a = fn.args
    ordered = list(a.posonlyargs) + list(a.args)
    tainted = set()
    for i, p in enumerate(ordered):
        if i in static_nums or p.arg in static_names or p.arg == "self":
            continue
        tainted.add(p.arg)
    for p in a.kwonlyargs:
        if p.arg not in static_names:
            tainted.add(p.arg)
    if a.vararg:
        tainted.add(a.vararg.arg)
    if a.kwarg:
        tainted.add(a.kwarg.arg)
    return tainted


def _propagate_taint(fn, tainted: set[str]) -> set[str]:
    """Forward-propagate taint through simple assignments (two passes
    cover loop-carried names)."""
    body = fn.body if not isinstance(fn, ast.Lambda) else []
    for _ in range(2):
        for node in ast.walk(ast.Module(body=list(body),
                                        type_ignores=[])):
            targets = None
            value = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
                value = node.value
            elif isinstance(node, ast.For):
                targets, value = [node.target], node.iter
            elif isinstance(node, (ast.comprehension,)):
                targets, value = [node.target], node.iter
            if targets is None or value is None:
                continue
            if _names_in(value) & tainted:
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            tainted.add(n.id)
    return tainted


def _analyze_jit_body(ctx: _FileContext, fn, tainted: set[str]):
    nodes = fn.body if not isinstance(fn, ast.Lambda) else [fn.body]
    wrapper = ast.Module(body=[], type_ignores=[])
    for stmt in nodes:
        wrapper.body.append(stmt)
    fname = getattr(fn, "name", "<lambda>")
    for node in ast.walk(wrapper):
        if isinstance(node, ast.Call):
            tgt = _dotted(node.func)
            arg_names = set()
            for a in list(node.args) + [k.value for k in node.keywords]:
                arg_names |= _names_in(a)
            if (tgt and tgt.split(".")[0] in _NP_ALIASES
                    and arg_names & tainted):
                yield Violation(
                    ctx.path, node.lineno, "tracer-hygiene",
                    f"{tgt}() applied to traced value in jitted "
                    f"'{fname}': numpy concretizes tracers (host "
                    "round-trip or ConcretizationTypeError); use jnp")
            elif (tgt in ("float", "int", "bool")
                  and arg_names & tainted):
                yield Violation(
                    ctx.path, node.lineno, "tracer-hygiene",
                    f"{tgt}() cast of traced value in jitted "
                    f"'{fname}' forces concretization")
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr == "item"
                  and _names_in(node.func.value) & tainted):
                yield Violation(
                    ctx.path, node.lineno, "tracer-hygiene",
                    f".item() on traced value in jitted '{fname}' "
                    "forces a device sync")
        elif isinstance(node, (ast.If, ast.While)):
            test = node.test
            # Identity tests (x is None) never read the traced value.
            if (isinstance(test, ast.Compare)
                    and all(isinstance(op, (ast.Is, ast.IsNot))
                            for op in test.ops)):
                continue
            if _names_in(test) & tainted:
                kind = "if" if isinstance(node, ast.If) else "while"
                yield Violation(
                    ctx.path, test.lineno, "tracer-hygiene",
                    f"python `{kind}` on traced value in jitted "
                    f"'{fname}': branch is resolved at trace time "
                    "(use jnp.where / lax.cond)")


def check_tracer_hygiene(ctx: _FileContext):
    for fn, nums, names in _jit_targets(ctx):
        tainted = _tainted_params(fn, nums, names)
        if not tainted:
            continue
        tainted = _propagate_taint(fn, set(tainted))
        yield from _analyze_jit_body(ctx, fn, tainted)


# ---------------------------------------------------------------------------
# Rule: unlocked-shared-write
# ---------------------------------------------------------------------------

_MUTATORS = ("append", "extend", "insert", "add", "update", "clear",
             "pop", "popitem", "remove", "discard", "setdefault",
             "move_to_end", "sort")
_LOCK_CTORS = ("threading.Lock", "threading.RLock", "threading.Condition",
               "Lock", "RLock", "Condition")
_SYNC_CTORS = _LOCK_CTORS + ("queue.Queue", "Queue", "threading.Event",
                             "Event", "queue.LifoQueue",
                             "queue.PriorityQueue")
_THREAD_CTORS = ("threading.Thread", "Thread")
_POOL_CTORS = ("ThreadPoolExecutor",
               "concurrent.futures.ThreadPoolExecutor")


def _self_attr(node: ast.AST) -> str | None:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _MethodInfo:
    def __init__(self, node):
        self.node = node
        self.write_nodes: list[tuple[str, ast.AST]] = []  # attr, ast node
        # (attr, line, locked, kind) — kind "rmw" | "rebind"
        self.writes: list[tuple[str, int, bool, str]] = []
        self.reads: set[str] = set()
        self.calls: set[str] = set()    # self.X() method calls


def _scan_class(cls: ast.ClassDef, par: dict):
    methods: dict[str, _MethodInfo] = {}
    workers: set[str] = set()
    lock_attrs: set[str] = set()
    sync_attrs: set[str] = set()

    for item in cls.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        mi = _MethodInfo(item)
        methods[item.name] = mi
        for node in ast.walk(item):
            if isinstance(node, ast.Call):
                tgt = _dotted(node.func)
                if tgt in _THREAD_CTORS:
                    for kw in node.keywords:
                        if kw.arg == "target":
                            attr = _self_attr(kw.value)
                            if attr:
                                workers.add(attr)
                elif (isinstance(node.func, ast.Attribute)
                      and node.func.attr == "submit" and node.args):
                    attr = _self_attr(node.args[0])
                    if attr:
                        workers.add(attr)
                if isinstance(node.func, ast.Attribute):
                    if _self_attr(node.func) is not None:
                        # self.method(...)
                        mi.calls.add(node.func.attr)
                    else:
                        m_attr = _self_attr(node.func.value)
                        if m_attr is not None and \
                                node.func.attr in _MUTATORS:
                            # self.attr.append(...) etc.
                            mi.write_nodes.append((m_attr, node))
                if item.name == "__init__" and tgt in _SYNC_CTORS:
                    assign = par.get(node)
                    if isinstance(assign, ast.Assign):
                        for t in assign.targets:
                            attr = _self_attr(t)
                            if attr:
                                sync_attrs.add(attr)
                                if tgt in _LOCK_CTORS:
                                    lock_attrs.add(attr)
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    elts = (t.elts if isinstance(t, (ast.Tuple, ast.List))
                            else [t])
                    for e in elts:
                        attr = _self_attr(e)
                        if attr:
                            mi.write_nodes.append((attr, node))
            elif isinstance(node, ast.Attribute) and isinstance(
                    node.ctx, ast.Load):
                attr = _self_attr(node)
                if attr:
                    mi.reads.add(attr)
    # Lock coverage is resolved after the whole class is scanned, so a
    # lock attribute declared below its first use still counts.  Kind
    # "rmw" = read-modify-write (AugAssign / container mutator) — the
    # lost-update shape; "rebind" = plain assignment.
    for mi in methods.values():
        mi.writes = [(attr, node.lineno,
                      _under_lock(node, par, lock_attrs),
                      "rebind" if isinstance(node, ast.Assign) else "rmw")
                     for attr, node in mi.write_nodes]
    return methods, workers, lock_attrs, sync_attrs


def _under_lock(node: ast.AST, par: dict, lock_attrs: set[str]) -> bool:
    for anc in _ancestors(node, par):
        if isinstance(anc, ast.With):
            for item in anc.items:
                attr = _self_attr(item.context_expr)
                if attr and (attr in lock_attrs
                             or "lock" in attr.lower()):
                    return True
    return False


def check_thread_discipline(ctx: _FileContext):
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        methods, workers, lock_attrs, sync_attrs = _scan_class(
            cls, ctx.parents)
        if not workers and not lock_attrs:
            continue

        # Worker-reachable closure over self.X() calls.
        reach = set(workers)
        frontier = list(workers)
        while frontier:
            m = frontier.pop()
            if m not in methods:
                continue
            for callee in methods[m].calls:
                if callee in methods and callee not in reach:
                    reach.add(callee)
                    frontier.append(callee)

        worker_writes: dict[str, list] = {}
        worker_reads: set[str] = set()
        caller_access: set[str] = set()
        caller_writes: dict[str, list] = {}
        for name, mi in methods.items():
            if name == "__init__":
                continue
            if name in reach:
                for a, ln, locked, _kind in mi.writes:
                    worker_writes.setdefault(a, []).append((ln, locked,
                                                            name))
                worker_reads |= mi.reads
            else:
                for a, ln, locked, _kind in mi.writes:
                    caller_writes.setdefault(a, []).append((ln, locked,
                                                            name))
                caller_access |= mi.reads
                caller_access |= {a for a, _, _, _ in mi.writes}

        flagged: set[tuple[int, str]] = set()

        def flag(attr, ln, method, side):
            if (ln, attr) in flagged:
                return None
            flagged.add((ln, attr))
            return Violation(
                ctx.path, ln, "unlocked-shared-write",
                f"'{cls.name}.{attr}' written in {method}() without "
                f"the lock but shared with the {side} thread; guard "
                "with the class lock or route through queue.Queue/"
                "Event")

        if workers:
            for attr, writes in worker_writes.items():
                if attr in sync_attrs or attr not in caller_access:
                    continue
                for ln, locked, m in writes:
                    if not locked:
                        v = flag(attr, ln, m, "caller")
                        if v:
                            yield v
            for attr, writes in caller_writes.items():
                if attr in sync_attrs:
                    continue
                if attr not in worker_reads and attr not in worker_writes:
                    continue
                for ln, locked, m in writes:
                    if not locked:
                        v = flag(attr, ln, m, "worker")
                        if v:
                            yield v
        if lock_attrs:
            # Lock-owning class: every non-init READ-MODIFY-WRITE
            # (+=, container mutators — the lost-update shape) must
            # hold the lock.  The ChunkStore discipline: `get`/`put`
            # run on the prefetch thread and the main thread alike, so
            # there is no single-threaded method to exempt.  Plain
            # rebinds (e.g. a thread handle) are only flagged when the
            # worker/caller sharing analysis above proves them shared.
            for name, mi in methods.items():
                if name == "__init__":
                    continue
                for attr, ln, locked, kind in mi.writes:
                    if attr in sync_attrs or locked or kind != "rmw":
                        continue
                    if (ln, attr) in flagged:
                        continue
                    flagged.add((ln, attr))
                    yield Violation(
                        ctx.path, ln, "unlocked-shared-write",
                        f"'{cls.name}.{attr}' mutated in {name}() "
                        f"outside the class lock ({sorted(lock_attrs)})"
                        "; lock-owning classes mutate shared state "
                        "under it")


# ---------------------------------------------------------------------------
# Rule: accumulator-dtype
# ---------------------------------------------------------------------------


def _mentions_f32_or_device(node: ast.AST) -> str | None:
    for n in ast.walk(node):
        d = _dotted(n) if isinstance(n, (ast.Attribute, ast.Name)) else None
        if d and d.split(".")[0] == "jnp":
            return "jnp (device fold)"
        if d and d.endswith("float32"):
            return "float32 cast"
        if isinstance(n, ast.Constant) and n.value == "float32":
            return "float32 cast"
    return None


def check_accumulator_dtype(ctx: _FileContext):
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        names = {m.name for m in cls.body
                 if isinstance(m, ast.FunctionDef)}
        if not {"update", "result"} <= names:
            continue
        for method in cls.body:
            if not isinstance(method, ast.FunctionDef):
                continue
            for node in ast.walk(method):
                if isinstance(node, ast.AugAssign):
                    attr = _self_attr(node.target)
                    if attr is None:
                        continue
                    why = _mentions_f32_or_device(node.value)
                    if why:
                        yield Violation(
                            ctx.path, node.lineno, "accumulator-dtype",
                            f"accumulator '{cls.name}.{attr}' folds "
                            f"through {why}; streaming metrics "
                            "accumulate on host in float64")


# ---------------------------------------------------------------------------
# Rule: env-read
# ---------------------------------------------------------------------------

_ENV_SANCTIONED_FILES = ("config.py",)


def check_env_read(ctx: _FileContext):
    if os.path.basename(ctx.path) in _ENV_SANCTIONED_FILES:
        return
    for node in ast.walk(ctx.tree):
        bad = None
        if isinstance(node, ast.Attribute) and _dotted(node) in (
                "os.environ",):
            bad = "os.environ"
        elif isinstance(node, ast.Call) and _dotted(node.func) in (
                "os.getenv", "getenv"):
            bad = "os.getenv"
        elif (isinstance(node, ast.Name) and node.id == "environ"
              and isinstance(node.ctx, ast.Load)):
            bad = "environ"
        if bad:
            yield Violation(
                ctx.path, node.lineno, "env-read",
                f"raw {bad} read; route through "
                "photon_ml_tpu.config.read_env (the sanctioned "
                "registry) so every env knob is discoverable")


# ---------------------------------------------------------------------------
# Rule: naked-clock
# ---------------------------------------------------------------------------

_WALL_CLOCKS = ("time.time",)


def _calls_wall_clock(node: ast.AST) -> bool:
    return any(isinstance(n, ast.Call)
               and _dotted(n.func) in _WALL_CLOCKS
               for n in ast.walk(node))


def check_naked_clock(ctx: _FileContext):
    """Durations must come from a monotonic clock.

    ``time.time()`` is the wall clock: it steps under NTP adjustment
    and suspend/resume, so ``time.time() - t0`` can go negative or jump
    by seconds — every phase timer, bench number, and telemetry span in
    the repo uses ``monotonic``/``perf_counter`` instead (the ISSUE-7
    telemetry tier made timing a first-class output, so a wall-clock
    duration is now a data-corruption bug, not just jitter).  Flags
    subtractions where either operand is a direct ``time.time()`` call
    or a name assigned from one; epoch TIMESTAMPS (no subtraction) stay
    legal, and deliberate wall-clock math can carry a waiver."""
    def _scope(node: ast.AST):
        """Nearest enclosing function (None = module scope) — plain
        names are tainted PER FUNCTION, so `t0 = time.time()` in one
        function cannot flag another function's perf_counter `t0`
        subtraction (reuse of conventional names is the norm)."""
        for anc in _ancestors(node, ctx.parents):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return anc
        return None

    clock_names: dict = {}         # scope id -> set of tainted names
    attr_names: set[str] = set()   # self.<attr> taint is class/file-wide
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            if _dotted(node.value.func) in _WALL_CLOCKS:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        clock_names.setdefault(
                            id(_scope(node)), set()).add(t.id)
                    else:
                        attr = _self_attr(t)
                        if attr:
                            attr_names.add(attr)

    def tainted(side: ast.AST, scoped: set[str]) -> bool:
        if _calls_wall_clock(side):
            return True
        for n in ast.walk(side):
            if (isinstance(n, ast.Name) and n.id in scoped
                    and isinstance(n.ctx, ast.Load)):
                return True
            attr = _self_attr(n)
            if attr is not None and attr in attr_names:
                return True
        return False

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub):
            scoped = clock_names.get(id(_scope(node)), set())
            if tainted(node.left, scoped) or tainted(node.right, scoped):
                yield Violation(
                    ctx.path, node.lineno, "naked-clock",
                    "duration arithmetic on time.time(): the wall "
                    "clock steps under NTP/suspend; use "
                    "time.monotonic() or time.perf_counter()")


# ---------------------------------------------------------------------------
# Rule: metric-name
# ---------------------------------------------------------------------------

# Dotted lowercase identifier with at least two segments
# ("namespace.metric"): the report, the bench telemetry block, and the
# history extractor all address metrics by dotted path, so a flat or
# mixed-case name silently falls out of every dashboard slice.
_METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z][a-z0-9_]*)+$")
_METRIC_FNS = ("count", "gauge", "observe")
# Receivers that identify the metrics registry at a call site: the
# module-level helpers, the conventional session handles, and the
# session's own methods.  Keyed narrowly so ``line.count(",")`` (str)
# or a container's ``.count`` can never false-positive.
_METRIC_RECEIVERS = ("telemetry", "t", "tel", "self", "self._t")


def check_metric_name(ctx: _FileContext):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        func = node.func
        if not (isinstance(func, ast.Attribute)
                and func.attr in _METRIC_FNS):
            continue
        recv = _dotted(func.value)
        if recv not in _METRIC_RECEIVERS:
            continue
        arg = node.args[0]
        if not (isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)):
            continue            # dynamic names: the caller's contract
        if not _METRIC_NAME_RE.match(arg.value):
            yield Violation(
                ctx.path, node.lineno, "metric-name",
                f"metric name {arg.value!r} is not a dotted lowercase "
                "identifier (want namespace.metric, e.g. "
                "'solver.sweeps'); flat or mixed-case names fall out "
                "of the report/history metric paths")


# ---------------------------------------------------------------------------
# Rule: swallowed-exception
# ---------------------------------------------------------------------------

# A call through any of these shapes counts as REPORTING the failure:
#   * attribute calls whose method name is a logging/telemetry verb
#     (logger.warning, log.event, telemetry.thread_exception, ...);
#   * calls rooted at the logging/warnings modules (logging.warning,
#     warnings.warn).
_REPORTING_ATTRS = frozenset({
    "debug", "info", "warning", "warn", "error", "exception",
    "critical", "log", "event", "heartbeat", "thread_exception",
})
_REPORTING_ROOTS = ("logging", "warnings")


def _handler_reports(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            func = node.func
            if (isinstance(func, ast.Attribute)
                    and func.attr in _REPORTING_ATTRS):
                return True
            d = _dotted(func)
            if d and d.split(".")[0] in _REPORTING_ROOTS:
                return True
    return False


def _handler_discards(handler: ast.ExceptHandler) -> bool:
    """True when the handler body does NOTHING with the failure:
    only ``pass``/``continue``/``break``, bare or constant ``return``,
    and constant expressions (docstrings).  A handler that computes a
    fallback, retries with new state, or mutates anything is HANDLING
    the error — different contract, not this rule's."""
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Return):
            if stmt.value is None or isinstance(stmt.value,
                                                ast.Constant):
                continue
            return False
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                     ast.Constant):
            continue
        return False
    return True


def check_swallowed_exception(ctx: _FileContext):
    """An ``except`` that silently discards the failure hides it: the
    run proceeds on wrong/partial state and the forensic trail has
    nothing (ISSUE 9 — fault tolerance is only honest when every
    absorbed failure is reported, handled with a real fallback, or
    explicitly waived as best-effort).  The waiver's mandatory reason
    IS the documentation of why silence is correct at that site."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _handler_discards(node) or _handler_reports(node):
            continue
        what = (_dotted(node.type) if node.type is not None
                else "BaseException")
        yield Violation(
            ctx.path, node.lineno, "swallowed-exception",
            f"except {what or '...'} handler silently discards the "
            "failure: report it (logger/telemetry), handle it with a "
            "real fallback, or waive with a reason documenting why "
            "best-effort silence is correct here")


# ---------------------------------------------------------------------------
# Rule: eternal-wait
# ---------------------------------------------------------------------------

# Zero-argument blocking calls that wait forever without a timeout.
# The zero-arg requirement keeps dict.get(key) / str.join(seq) /
# path.join(a, b) out by construction: the flagged shapes are
# queue.Queue.get(), threading.Event.wait() / Condition.wait(), and
# Thread.join().
_ETERNAL_ZERO_ARG = ("get", "wait", "join")


def _has_timeout_kw(call: ast.Call) -> bool:
    return any(kw.arg in ("timeout", "timeout_s") and not (
        isinstance(kw.value, ast.Constant) and kw.value.value is None)
        for kw in call.keywords)


def check_eternal_wait(ctx: _FileContext):
    """A thread-spawning class owns at least one cross-thread wait; a
    wait with NO timeout turns a dead peer into a silently pinned
    thread (the wedged-replica outage class, ISSUE 13).  Flags
    ``.get()`` / ``.wait()`` / ``.join()`` calls with neither a
    positional argument nor a timeout keyword, and ``.recv(...)``
    (socket reads — the timeout lives in ``settimeout``, which static
    analysis cannot prove was called) inside classes that construct
    ``threading.Thread`` / ``ThreadPoolExecutor``.  Deliberately
    unbounded waits (a ``close()`` that provably enqueues a sentinel,
    a main thread parked on a stop event) carry a waiver naming the
    termination argument."""
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        spawns = any(
            isinstance(n, ast.Call)
            and _dotted(n.func) in _THREAD_CTORS + _POOL_CTORS
            for n in ast.walk(cls))
        if not spawns:
            continue
        for node in ast.walk(cls):
            if not isinstance(node, ast.Call) or not isinstance(
                    node.func, ast.Attribute):
                continue
            name = node.func.attr
            if name in _ETERNAL_ZERO_ARG:
                if node.args or _has_timeout_kw(node):
                    continue
                recv = _dotted(node.func.value) or "<expr>"
                yield Violation(
                    ctx.path, node.lineno, "eternal-wait",
                    f"{recv}.{name}() blocks with no timeout in "
                    f"thread-spawning class '{cls.name}': a dead peer "
                    "pins this thread forever — pass a timeout (poll) "
                    "or waive with the termination argument")
            elif name == "recv" and not _has_timeout_kw(node):
                recv = _dotted(node.func.value) or "<expr>"
                yield Violation(
                    ctx.path, node.lineno, "eternal-wait",
                    f"{recv}.recv() in thread-spawning class "
                    f"'{cls.name}': socket reads block forever unless "
                    "settimeout() was called — set one (or waive "
                    "naming where the timeout is applied)")


# ---------------------------------------------------------------------------
# Rule: collective-in-host-branch
# ---------------------------------------------------------------------------

# Cross-device/cross-host collectives: every participant must reach the
# call or the fleet deadlocks at the barrier.
_COLLECTIVE_FNS = ("psum", "psum_scatter", "pmean", "pmax", "pmin",
                   "all_gather", "all_to_all", "ppermute", "pshuffle")


def _divergent_host_test(test: ast.AST) -> bool:
    """Does a branch condition read the PROCESS IDENTITY — a value that
    differs per host, so the branch arms diverge across the fleet?
    ``process_index()`` calls and ``host_id`` reads (the FleetContext
    field) qualify; ``process_count()`` does not — it is uniform."""
    for n in ast.walk(test):
        if isinstance(n, ast.Call):
            d = _dotted(n.func)
            if d and d.split(".")[-1] == "process_index":
                return True
        elif isinstance(n, ast.Attribute) and n.attr == "host_id":
            return True
        elif (isinstance(n, ast.Name) and n.id == "host_id"
              and isinstance(n.ctx, ast.Load)):
            return True
    return False


def check_collective_in_host_branch(ctx: _FileContext):
    """A collective (psum/all_gather/...) lexically inside a branch
    conditioned on the process identity (``jax.process_index()`` /
    ``host_id``) is a fleet deadlock: only SOME hosts reach the
    barrier, the rest wait forever (ISSUE 16 — the sharded streaming
    tier pads ragged shards with empty-chunk sentinels precisely so
    every host runs the same collective count).  Hoist the collective
    out of the branch, make the condition uniform across hosts, or
    waive with the reason every host still participates."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if not d or d.split(".")[-1] not in _COLLECTIVE_FNS:
            continue
        for anc in _ancestors(node, ctx.parents):
            # A def boundary ends the lexical branch: a helper merely
            # DEFINED under a host-conditional may be called by every
            # host (lambdas stay transparent — jax collectives live in
            # lambdas invoked in place).
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
            if (isinstance(anc, (ast.If, ast.While, ast.IfExp))
                    and _divergent_host_test(anc.test)):
                yield Violation(
                    ctx.path, node.lineno, "collective-in-host-branch",
                    f"{d.split('.')[-1]} inside a branch on the process "
                    "identity (process_index()/host_id, line "
                    f"{anc.lineno}): hosts that skip the branch never "
                    "reach the collective and the fleet deadlocks — "
                    "hoist it out or make the condition uniform")
                break


# ---------------------------------------------------------------------------
# Rule: while-loop-carry-dtype
# ---------------------------------------------------------------------------


def _literal_class(node: ast.AST) -> str | None:
    """Best-effort dtype CLASS ('bool'/'int'/'float') of a carry-init
    expression, from literal structure only.  None = not inferable
    (Name, general Call, ...) — such positions are never flagged."""
    if isinstance(node, ast.Constant):
        if isinstance(node.value, bool):
            return "bool"
        if isinstance(node.value, int):
            return "int"
        if isinstance(node.value, float):
            return "float"
        return None
    if isinstance(node, ast.Compare):
        return "bool"
    if isinstance(node, ast.UnaryOp):
        return _literal_class(node.operand)
    if isinstance(node, ast.Call):
        d = _dotted(node.func) or ""
        tail = d.split(".")[-1]

        def cls_of(name: str) -> str | None:
            if "bool" in name:
                return "bool"
            if "int" in name:
                return "int"
            if "float" in name or name == "double":
                return "float"
            return None

        # An explicit dtype argument wins (jnp.asarray(0, jnp.int32),
        # jnp.zeros(n, dtype=jnp.float32), ...).
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            dt = _dotted(arg)
            if dt is not None:
                c = cls_of(dt.split(".")[-1])
                if c:
                    return c
        if cls_of(tail):                       # jnp.int32(...), float(...)
            return cls_of(tail)
        if tail in ("asarray", "array") and node.args:
            return _literal_class(node.args[0])
        if tail in ("logical_and", "logical_or", "logical_not"):
            return "bool"
        if tail in ("zeros", "ones", "full", "zeros_like", "ones_like"):
            return "float"                     # jnp default dtype
    return None


def _is_float_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.UnaryOp):
        node = node.operand
    return (isinstance(node, ast.Constant)
            and isinstance(node.value, float))


def _is_number_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.UnaryOp):
        node = node.operand
    return (isinstance(node, ast.Constant)
            and not isinstance(node.value, bool)
            and isinstance(node.value, (int, float)))


def _is_f64_cast(node: ast.AST) -> bool:
    """``np.float64(...)`` / ``jnp.float64(...)`` / ``np.double(...)``
    — a concrete f64 value (not a weak Python literal) whose fold
    promotes an f32 carry under x64."""
    if not isinstance(node, ast.Call):
        return False
    d = _dotted(node.func) or ""
    return d.split(".")[-1] in ("float64", "double")


def _carry_classes(body, init) -> dict:
    """{carry_name: dtype_class | None} for a while-body function.

    Names come from the body's single carry parameter: the parameter
    itself (single-leaf carry), or the targets of a top-level
    ``a, b, c = <param>`` unpack matched positionally against a literal
    init tuple at the call site.  Dataclass carries and cross-function
    inits resolve to no names — never flagged (the rule only fires
    where the init dtype is statically known)."""
    args = body.args.args
    if len(args) != 1:
        return {}
    param = args[0].arg
    if not isinstance(init, (ast.Tuple, ast.List)):
        return {param: _literal_class(init)}
    if not isinstance(body, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return {}                      # lambda cannot tuple-unpack
    for st in body.body:
        if (isinstance(st, ast.Assign) and len(st.targets) == 1
                and isinstance(st.targets[0], (ast.Tuple, ast.List))
                and isinstance(st.value, ast.Name)
                and st.value.id == param):
            targets = st.targets[0].elts
            if len(targets) != len(init.elts):
                return {}
            return {t.id: _literal_class(e)
                    for t, e in zip(targets, init.elts)
                    if isinstance(t, ast.Name)}
    return {}


def check_while_carry_dtype(ctx: _FileContext):
    """A ``lax.while_loop`` body must return every carry leaf with the
    init's dtype — JAX rejects the mismatch at trace time with an
    opaque "body function output ... differs from the carry" error far
    from the offending expression.  The classic folds: a float literal
    into an int/bool carry (``it + 1.0`` on an int32 counter turns the
    leaf weak-f32), and an explicit f64 cast into an f32 carry.  Only
    carry names whose init dtype is statically inferable are checked;
    waive with the reason the fold provably preserves the dtype."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func) or ""
        if d.split(".")[-1] != "while_loop" or len(node.args) < 3:
            continue
        body_arg, init = node.args[1], node.args[2]
        body = None
        if isinstance(body_arg, ast.Lambda):
            body = body_arg
        elif isinstance(body_arg, ast.Name):
            # Nearest enclosing scope's def of that name (while bodies
            # are conventionally local helpers).
            for anc in (*_ancestors(node, ctx.parents), ctx.tree):
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Module)):
                    for n in ast.walk(anc):
                        if (isinstance(n, ast.FunctionDef)
                                and n.name == body_arg.id):
                            body = n
                            break
                if body is not None:
                    break
        if body is None:
            continue
        classes = _carry_classes(body, init)
        if not any(classes.values()):
            continue
        for sub in ast.walk(body):
            if isinstance(sub, ast.BinOp):
                pairs = ((sub.left, sub.right), (sub.right, sub.left))
            elif isinstance(sub, ast.AugAssign):
                pairs = ((sub.target, sub.value),)
            else:
                continue
            for carry_side, other in pairs:
                if not (isinstance(carry_side, ast.Name)
                        and carry_side.id in classes):
                    continue
                cls = classes[carry_side.id]
                if cls == "int" and _is_float_literal(other):
                    yield Violation(
                        ctx.path, sub.lineno, "while-loop-carry-dtype",
                        f"float literal folded into int carry "
                        f"'{carry_side.id}' (init at line "
                        f"{init.lineno}): the leaf turns weak-f32 and "
                        "the while_loop carry dtype check fails at "
                        "trace time — use an int literal or cast "
                        "explicitly outside the carry")
                    break
                if cls == "bool" and _is_number_literal(other):
                    yield Violation(
                        ctx.path, sub.lineno, "while-loop-carry-dtype",
                        f"numeric literal folded into bool carry "
                        f"'{carry_side.id}' (init at line "
                        f"{init.lineno}): the leaf leaves bool and the "
                        "while_loop carry dtype check fails at trace "
                        "time — use jnp.logical_* on bool carries")
                    break
                if cls is not None and _is_f64_cast(other):
                    yield Violation(
                        ctx.path, sub.lineno, "while-loop-carry-dtype",
                        f"float64 cast folded into carry "
                        f"'{carry_side.id}' (init at line "
                        f"{init.lineno}): under x64 the promoted leaf "
                        "no longer matches the f32 init — keep carry "
                        "arithmetic in the carry's own dtype")
                    break


# ---------------------------------------------------------------------------
# Rule: slow-unmarked (repo-level: needs the recorded durations)
# ---------------------------------------------------------------------------


def _is_slow_mark(node: ast.AST) -> bool:
    """Exactly ``[pytest.]mark.slow`` (optionally called) — a substring
    test would false-match e.g. a skipif reason mentioning "slow"."""
    if isinstance(node, ast.Call):
        node = node.func
    return (_dotted(node) or "").endswith("mark.slow")


def _test_has_slow(tree: ast.AST, func: str) -> bool:
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "pytestmark":
                    v = node.value
                    marks = (v.elts if isinstance(v, (ast.List, ast.Tuple))
                             else [v])
                    if any(_is_slow_mark(m) for m in marks):
                        return True
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == func:
            if any(_is_slow_mark(d) for d in node.decorator_list):
                return True
    return False


def check_slow_unmarked(root: str):
    dur_path = os.path.join(root, DURATIONS_FILE)
    if not os.path.exists(dur_path):
        return
    with open(dur_path) as f:
        recorded = json.load(f)
    durations = recorded.get("durations", recorded)
    by_func: dict[tuple[str, str], float] = {}
    for nodeid, secs in durations.items():
        if "::" not in nodeid:
            continue
        file_part, test_part = nodeid.split("::", 1)
        # Last :: segment = the function/method name (class-based tests
        # produce file.py::TestCls::test_x; ast.walk in _test_has_slow
        # visits methods, so the unqualified name is what matches).
        func = test_part.split("[", 1)[0].split("::")[-1]
        key = (file_part, func)
        by_func[key] = max(by_func.get(key, 0.0), float(secs))
    trees: dict[str, tuple] = {}
    for (file_part, func), secs in sorted(by_func.items()):
        if secs <= SLOW_THRESHOLD_S:
            continue
        path = os.path.join(root, file_part)
        if not os.path.exists(path):
            continue
        if path not in trees:
            with open(path) as f:
                src = f.read()
            ctx = _FileContext(path, src)   # parses once; .tree reused
            trees[path] = (ctx.tree, ctx)
        tree, ctx = trees[path]
        if _test_has_slow(tree, func):
            continue
        line = 1
        for node in ast.walk(tree):
            if isinstance(node, ast.FunctionDef) and node.name == func:
                line = node.lineno
                break
        v = Violation(
            path, line, "slow-unmarked",
            f"'{func}' measured {secs:.1f}s (> {SLOW_THRESHOLD_S:.0f}s "
            "threshold) in the recorded tier-1 run but lacks "
            "@pytest.mark.slow")
        if not ctx.waived(line, "slow-unmarked"):
            yield v


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------

_FILE_CHECKERS = (
    check_jit_in_function,
    check_tracer_hygiene,
    check_thread_discipline,
    check_accumulator_dtype,
    check_env_read,
    check_naked_clock,
    check_metric_name,
    check_swallowed_exception,
    check_eternal_wait,
    check_collective_in_host_branch,
    check_while_carry_dtype,
)


def check_source(source: str, path: str = "<fixture>",
                 rules=None) -> list[Violation]:
    """Run the per-file checkers over one source string (the unit-test
    surface for the fixture corpus)."""
    ctx = _FileContext(path, source)
    out: list[Violation] = []
    for checker in _FILE_CHECKERS:
        for v in checker(ctx):
            if rules is not None and v.rule not in rules:
                continue
            if not ctx.waived(v.line, v.rule):
                out.append(v)
    if rules is None or "bad-waiver" in rules:
        for line in ctx.bad_waivers:
            out.append(Violation(
                path, line, "bad-waiver",
                "photon-lint waiver without a (reason); every "
                "suppression must say why the contract does not apply"))
    return sorted(out, key=lambda v: (v.path, v.line, v.rule))


def _package_files(root: str) -> list[str]:
    pkg = os.path.join(root, "photon_ml_tpu")
    out = []
    for dirpath, _dirnames, filenames in os.walk(pkg):
        if "__pycache__" in dirpath:
            continue
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                out.append(os.path.join(dirpath, fn))
    return sorted(out)


def run_checks(root: str, rules=None, files=None):
    """All violations for the repo at ``root`` (package files + the
    recorded-duration test audit).  Returns (violations, files_checked).
    """
    targets = files if files is not None else _package_files(root)
    violations: list[Violation] = []
    for path in targets:
        with open(path) as f:
            source = f.read()
        try:
            violations.extend(check_source(source, path, rules=rules))
        except SyntaxError as e:
            if rules is None or "syntax-error" in rules:
                violations.append(Violation(
                    path, e.lineno or 1, "syntax-error", str(e)))
    if rules is None or "slow-unmarked" in rules:
        audited = list(check_slow_unmarked(root))
        if files is not None:
            # Explicit file list: the audit still runs (the JSON must
            # not claim a requested rule ran when it did not), scoped
            # to those files.
            wanted = {os.path.abspath(p) for p in targets}
            audited = [v for v in audited
                       if os.path.abspath(v.path) in wanted]
        violations.extend(audited)
    violations.sort(key=lambda v: (v.path, v.line, v.rule))
    return violations, len(targets)
