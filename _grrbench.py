"""Real-TPU GRR end-to-end probe at bench scale."""
import sys, time
import numpy as np
import jax
import jax.numpy as jnp

def log(m): print(m, file=sys.stderr, flush=True)

from photon_ml_tpu.data.batch import SparseBatch
from photon_ml_tpu.data.grr import build_grr_pair
from photon_ml_tpu.data.normalization import NormalizationContext
from photon_ml_tpu.ops import losses
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.utils.timing import measure

n, d, k = 1_000_000, 100_000, 30
rng = np.random.default_rng(0)
block = d // k
cols = ((np.arange(k, dtype=np.int64) * block)[None, :]
        + rng.integers(0, block, (n, k))).astype(np.int32)
vals = rng.normal(0, 1, (n, k)).astype(np.float32)
labels = (rng.uniform(size=n) < 0.5).astype(np.float32)

t0 = time.time()
pair = build_grr_pair(cols, vals, d)
log(f"GRR ETL: {time.time()-t0:.1f}s  row sts={pair.row_dir.n_supertiles} "
    f"(cap {pair.row_dir.cap}, spill {pair.row_dir.n_spill}) "
    f"col sts={pair.col_dir.n_supertiles} (cap {pair.col_dir.cap}, "
    f"spill {pair.col_dir.n_spill}) hot={pair.hot_ids.shape[0]}")

def mk(grr):
    return SparseBatch(
        values=jnp.asarray(vals), col_ids=jnp.asarray(cols),
        labels=jnp.asarray(labels),
        weights=jnp.ones((n,), jnp.float32),
        offsets=jnp.zeros((n,), jnp.float32),
        mask=jnp.ones((n,), jnp.float32),
        dim=d, grr=grr,
    )

obj = GLMObjective(loss=losses.LOGISTIC, reg=RegularizationContext.l2(1.0),
                   norm=NormalizationContext.identity())
w = jnp.asarray(rng.normal(0, 0.1, d), jnp.float32)

b_grr = mk(pair)
b_ell = mk(None)

# correctness on chip
v1, g1 = jax.jit(obj.value_and_gradient)(w, b_ell)
v2, g2 = jax.jit(obj.value_and_gradient)(w, b_grr)
log(f"value ell={float(v1):.4f} grr={float(v2):.4f}")
gerr = float(jnp.max(jnp.abs(g1 - g2)) / (jnp.max(jnp.abs(g1)) + 1e-9))
log(f"grad rel err: {gerr:.2e}")
assert abs(float(v1) - float(v2)) / abs(float(v1)) < 1e-4
assert gerr < 1e-3

# timing: scan of value+grad steps inside one jit (mirrors the solver loop)
def chain(w, batch, length=20):
    def body(c, _):
        v, g = obj.value_and_gradient(c, batch)
        return c - 1e-6 * g, None
    out, _ = jax.lax.scan(body, w, None, length=length)
    return out

for name, b in [("grr", b_grr), ("ell segsum", b_ell)]:
    f = jax.jit(lambda w, b=b: chain(w, b))
    t0 = time.time(); jax.block_until_ready(f(w)); log(f"{name} compile {time.time()-t0:.1f}s")
    s = measure(f, w, iters=3) / 20
    log(f"{name}: {s*1e3:.2f} ms/step  {n/s:.3e} ex/s")
    if name == "ell segsum":
        break
