"""Benchmark: fused GLM value+gradient pass at realistic sparse scale.

Measures the framework's hot loop — one fused (value, gradient)
evaluation of the logistic objective, the unit of work per optimizer
iteration (the reference's ``ValueAndGradientAggregator`` +
``treeAggregate`` round, SURVEY.md §2.2) — on whatever accelerator jax
provides (the driver runs this on one real TPU v5e chip).

Workload: n=1,000,000 examples, d=100,000 features, k=30 nnz/row
(KDD-2012-class sparsity).  THREE sparse layouts are timed on identical
data (round-2 verdict item: report them all, honestly):

- ``segment_sum``: plain ELL — XLA's scalar gather + scatter lowering
  (what a straight port produces; round 2's shipped path);
- ``colmajor``: transposed-ELL — scatter-free but still on XLA's scalar
  gather;
- ``grr``: the compiled gather-route-reduce plan executed by the Mosaic
  kernel (``data/grr.py`` + ``ops/grr_kernel.py``) — the production
  path (``TrainingConfig.sparse_layout`` AUTO on TPU).

Timing runs the step inside one jitted ``lax.scan`` (mirroring the
production solvers, where the whole optimize loop is a single device
program) — single-dispatch timings through the axon tunnel carry ~19 ms
of fixed per-call overhead and would swamp a ~15 ms kernel.

``vs_baseline``: the reference publishes no numbers (BASELINE.md), so
the ratio is best-XLA-layout time / GRR time — the speedup of the
framework's compiled plan over the best formulation XLA alone can run.
``roofline_fraction`` is achieved HBM traffic (counting every byte the
GRR plan actually streams, padding and index planes included) against
the v5e's 819 GB/s peak.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

V5E_PEAK_GBPS = 819.0


def _make_ell(n: int, d: int, k: int, seed: int = 0):
    """Vectorized synthetic ELL batch: unique col ids per row by
    stratified sampling (one column per d/k-wide block)."""
    rng = np.random.default_rng(seed)
    block = d // k
    cols = (np.arange(k, dtype=np.int64) * block)[None, :] + rng.integers(
        0, block, (n, k)
    )
    vals = rng.normal(0, 1, (n, k)).astype(np.float32)
    labels = (rng.uniform(size=n) < 0.5).astype(np.float32)
    return cols.astype(np.int32), vals, labels


def _grr_stream_bytes(pair) -> int:
    """Bytes the GRR plan actually moves per fused value+gradient step:
    both directions' (vals f32 + 3 route planes i8) streams — including
    each direction's second-level overflow plan — spill COO, table
    windows, and the dense hot side."""

    def direction_bytes(d_) -> int:
        from photon_ml_tpu.data.grr import GrrRangeSplit

        if isinstance(d_, GrrRangeSplit):
            return sum(direction_bytes(p) for p in d_.parts)
        slots = d_.n_supertiles * 16384
        b = slots * (4 + 3)                           # vals + g1/g2/g3
        b += d_.n_spill * 12                          # spill idx/seg/val
        if d_.dense_grid:
            # gw-major grid: the window block index only changes between
            # gw runs, so each [128,128] window streams ONCE per run;
            # the per-tile partials are written then re-read by the
            # reshape-sum reduction.
            b += d_.n_gw * 16384 * 4
            b += 2 * d_.n_supertiles * (16384 // d_.cap) * 4
        else:
            # Legacy order: one window is (re)streamed per supertile.
            b += d_.n_supertiles * 16384 * 4
        if d_.overflow is not None:
            b += direction_bytes(d_.overflow)
        return b

    total = direction_bytes(pair.row_dir) + direction_bytes(pair.col_dir)
    if pair.col_mid is not None:
        total += direction_bytes(pair.col_mid)
    total += int(np.prod(pair.x_hot.shape)) * 4 * 2   # dense side, 2 dirs
    return total


def main() -> None:
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.data.batch import SparseBatch
    from photon_ml_tpu.data.colmajor import build_colmajor
    from photon_ml_tpu.data.grr import build_grr_pair
    from photon_ml_tpu.data.normalization import NormalizationContext
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.ops.objective import GLMObjective
    from photon_ml_tpu.ops.regularization import RegularizationContext


    from photon_ml_tpu.data import grr as grr_mod

    n, d, k = 1_000_000, 100_000, 30
    platform = jax.devices()[0].platform
    print(f"platform={platform} n={n} d={d} k={k}", file=sys.stderr)

    cols, vals, labels = _make_ell(n, d, k)

    t0 = time.time()
    pair = build_grr_pair(cols, vals, d)
    etl_grr_s = time.time() - t0
    # Phase breakdown (host build per chain vs device-transfer fence):
    # the ETL number of record is self-diagnosing — round-4's
    # captured-vs-claimed discrepancy was the untimed plan transfer.
    etl_phases = {k_: round(v, 2)
                  for k_, v in grr_mod.last_build_phases.items()}
    t0 = time.time()
    cm = build_colmajor(cols, vals, d)
    etl_colmajor_s = time.time() - t0
    print(f"ETL: grr={etl_grr_s:.0f}s (phases {etl_phases}) "
          f"colmajor={etl_colmajor_s:.0f}s", file=sys.stderr)

    def mk(colmajor=None, grr=None):
        return SparseBatch(
            values=jnp.asarray(vals), col_ids=jnp.asarray(cols),
            labels=jnp.asarray(labels),
            weights=jnp.ones((n,), jnp.float32),
            offsets=jnp.zeros((n,), jnp.float32),
            mask=jnp.ones((n,), jnp.float32),
            dim=d, colmajor=colmajor, grr=grr,
        )

    obj = GLMObjective(
        loss=losses.LOGISTIC,
        reg=RegularizationContext.l2(1.0),
        norm=NormalizationContext.identity(),
    )
    w0 = jnp.asarray(np.random.default_rng(1).normal(0, 0.1, d), jnp.float32)

    from photon_ml_tpu.utils.timing import measure_scanned

    def step(w, batch):
        _, g = obj.value_and_gradient(w, batch)
        return w - 1e-6 * g

    results = {}
    # Scan lengths amortize per-dispatch overhead to <~2% of step time
    # for EVERY variant (advisor finding: unequal amortization biased
    # the cross-variant ratio): the production solvers run the WHOLE
    # optimize loop as one device program (lbfgs/tron while_loop), so
    # per-call dispatch/fence is measurement artifact, not production
    # cost — the axon tunnel costs ~100 ms per dispatch+fence round.
    # GRR at ~5 ms/step needs length 250; colmajor/segment_sum at
    # ~500 ms/step reach the same <~1% bias at length 20.
    variants = [
        ("grr", mk(grr=pair), 250, 2),
        ("colmajor", mk(colmajor=cm), 20, 2),
        ("segment_sum", mk(), 20, 2),
    ]
    for name, batch, length, iters in variants:
        t0 = time.time()
        s = measure_scanned(step, w0, batch, length=length, iters=iters)
        results[name] = s
        print(f"{name}: {s*1e3:.2f} ms/step "
              f"(measured in {time.time()-t0:.0f}s)", file=sys.stderr)

    t_grr = results["grr"]
    t_best_xla = min(results["colmajor"], results["segment_sum"])
    examples_per_sec = n / t_grr

    grr_bytes = _grr_stream_bytes(pair) + 6 * n * 4 + 4 * d * 4
    achieved_gbps = grr_bytes / t_grr / 1e9
    roofline = achieved_gbps / V5E_PEAK_GBPS if platform == "tpu" else None

    # Power-law-columns variant (round-4 verdict item #1: the uniform
    # bench hides exactly the skew defect the column-range split fixes).
    # Reciprocal popularity P(col) ∝ 1/(col+x0) puts ~45% of entries in
    # table window 0 at this shape — the KDD/CTR profile.
    rng = np.random.default_rng(3)
    x0 = float(d) / 14.0
    u = rng.uniform(size=(n, k))
    cols_p = np.minimum(x0 * np.exp(u * np.log((d + x0) / x0)) - x0,
                        d - 1).astype(np.int32)
    t0 = time.time()
    pair_p = build_grr_pair(cols_p, vals, d)
    etl_grr_powerlaw_s = time.time() - t0
    row_stats = pair_p.row_dir.plan_stats()
    t0 = time.time()
    t_grr_p = measure_scanned(step, w0, mk(grr=pair_p), length=250,
                              iters=2)
    print(f"grr powerlaw: {t_grr_p*1e3:.2f} ms/step "
          f"(measured in {time.time()-t0:.0f}s; row spill_frac="
          f"{row_stats['spill_frac']:.4f} coo_frac="
          f"{row_stats['coo_frac']:.5f} caps={row_stats['cap']})",
          file=sys.stderr)
    powerlaw = {
        "step_ms_grr": round(t_grr_p * 1e3, 3),
        "etl_grr_s": round(etl_grr_powerlaw_s, 1),
        "row_spill_frac": round(row_stats["spill_frac"], 4),
        "row_coo_frac": round(row_stats["coo_frac"], 5),
        "row_caps": row_stats["cap"],
        "range_bounds": row_stats.get("bounds"),
    }

    # Chunked (beyond-HBM) regime: one full-dataset value+gradient pass
    # through resident ELL chunks (data/chunked_batch.py +
    # optim/streaming.py) — the class that trains 3x10^7 examples on
    # one chip (PERF.md).  Timed EAGERLY including per-chunk dispatch,
    # because that IS this class's production cost (the streaming
    # solver cannot fuse the pass into one device program).
    from photon_ml_tpu.data.chunked_batch import build_chunked_batch
    from photon_ml_tpu.data.sparse_rows import SparseRows
    from photon_ml_tpu.optim.streaming import ChunkedGLMObjective

    t0 = time.time()
    rows_sp = SparseRows.from_flat(
        np.arange(n + 1, dtype=np.int64) * k,
        cols.reshape(-1).astype(np.int64), vals.reshape(-1))
    cobj = ChunkedGLMObjective(
        obj, build_chunked_batch(rows_sp, d, labels, n_chunks=4,
                                 layout="ell"),
        max_resident=4)
    etl_chunked_s = time.time() - t0
    jax.block_until_ready(cobj.value_and_gradient(w0)[1])  # compile+place
    t0 = time.time()
    chunk_iters = 5
    for _ in range(chunk_iters):
        # Fence EVERY pass: the streaming solver syncs after each
        # evaluation (the line search reads the value on host), so a
        # per-pass fence is production cost, not artifact.
        jax.block_until_ready(cobj.value_and_gradient(w0)[1])
    t_pass = (time.time() - t0) / chunk_iters
    print(f"chunked (4 ELL chunks, fully resident): {t_pass*1e3:.1f} "
          f"ms/pass (etl {etl_chunked_s:.0f}s)", file=sys.stderr)
    chunked = {
        "pass_ms": round(t_pass * 1e3, 1),
        "examples_per_sec": round(n / t_pass, 1),
        "n_chunks": 4,
        # All chunks held in HBM across passes — the resident end of
        # the chunked regime (no per-pass transfer timed); streaming
        # re-placement costs are link-dependent (PERF.md).
        "max_resident": 4,
        "regime": "resident",
        "layout": "ell",
        "etl_s": round(etl_chunked_s, 1),
    }

    print(json.dumps({
        "metric": "fused sparse GLM value+gradient throughput "
                  f"(n=1e6,d=1e5,k=30,{platform},GRR layout)",
        "value": round(examples_per_sec, 1),
        "unit": "examples/sec",
        "vs_baseline": round(t_best_xla / t_grr, 3),
        "step_ms_grr": round(t_grr * 1e3, 3),
        "step_ms_colmajor": round(results["colmajor"] * 1e3, 3),
        "step_ms_segment_sum": round(results["segment_sum"] * 1e3, 3),
        "achieved_hbm_gbps": round(achieved_gbps, 1),
        "roofline_fraction": (round(roofline, 4)
                              if roofline is not None else None),
        "baseline_note": "vs_baseline = best XLA layout (colmajor or "
                         "segment_sum) over the GRR compiled plan; "
                         "reference publishes no numbers",
        "etl_grr_s": round(etl_grr_s, 1),
        "etl_phases": etl_phases,
        "etl_colmajor_s": round(etl_colmajor_s, 1),
        "powerlaw": powerlaw,
        "chunked": chunked,
    }))


if __name__ == "__main__":
    main()
