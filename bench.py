"""Benchmark: fused GLM value+gradient pass at realistic sparse scale.

Measures the framework's hot loop — one fused (value, gradient)
evaluation of the logistic objective, the unit of work per optimizer
iteration (the reference's ``ValueAndGradientAggregator`` +
``treeAggregate`` round, SURVEY.md §2.2) — on whatever accelerator jax
provides (the driver runs this on one real TPU v5e chip).

Workload: n=1,000,000 examples, d=100,000 features, k=30 nnz/row
(KDD-2012-class sparsity).  THREE sparse layouts are timed on identical
data (round-2 verdict item: report them all, honestly):

- ``segment_sum``: plain ELL — XLA's scalar gather + scatter lowering
  (what a straight port produces; round 2's shipped path);
- ``colmajor``: transposed-ELL — scatter-free but still on XLA's scalar
  gather;
- ``grr``: the compiled gather-route-reduce plan executed by the Mosaic
  kernel (``data/grr.py`` + ``ops/grr_kernel.py``) — the production
  path (``TrainingConfig.sparse_layout`` AUTO on TPU).

Timing runs the step inside one jitted ``lax.scan`` (mirroring the
production solvers, where the whole optimize loop is a single device
program) — single-dispatch timings through the axon tunnel carry ~19 ms
of fixed per-call overhead and would swamp a ~15 ms kernel.

``vs_baseline``: the reference publishes no numbers (BASELINE.md), so
the ratio is best-XLA-layout time / GRR time — the speedup of the
framework's compiled plan over the best formulation XLA alone can run.
``roofline_fraction`` is achieved HBM traffic (counting every byte the
GRR plan actually streams, padding and index planes included) against
the v5e's 819 GB/s peak.

Budgeted-section contract (round-5 verdict: the bench outgrew the
driver's capture window and the round had NO perf number of record —
``rc: 124 / parsed: null`` must never happen again):

- ``--section A[,B...]`` runs only those sections; default is
  ``etl,cached,grr,segment_sum,colmajor`` (``powerlaw`` and ``chunked``
  are opt-in extras).
- ``--budget-s N`` (default 840) is a wall-clock budget: before each
  section its cost is estimated (scaled to the shape) and sections
  that do not fit are SKIPPED and recorded, so the process always
  exits 0 in budget with the measurements it did make.
- The LAST stdout line is always one machine-parseable JSON object
  (progress goes to stderr); a section failure is recorded in
  ``"errors"`` instead of killing the run.
- ``cached`` measures the warm path: loading the GRR plan from the
  on-disk plan cache (``photon_ml_tpu.cache``) vs the cold build the
  ``etl`` section always performs (the etl number stays honest — it
  never reads the cache).  The persistent XLA compilation cache is ON
  by default (under ``--cache-dir``), so a second driver run also
  skips the multi-minute scan compiles.
- ``--n/--d/--k`` shrink the shape (CI runs a tiny-shape ``etl``
  section as a fast-tier test so budget regressions fail in tests, not
  in the driver).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
import traceback
from contextlib import ExitStack

import numpy as np

# Single source of truth for the platform peak (ISSUE 8): the telemetry
# device-accounting table and this bench must emit the SAME roofline
# basis or one record would carry two disagreeing estimates.
from photon_ml_tpu.telemetry.device import PLATFORM_PEAK_GBPS

V5E_PEAK_GBPS = PLATFORM_PEAK_GBPS["tpu"][0]

DEFAULT_SECTIONS = ("etl", "cached", "grr", "segment_sum", "colmajor")
ALL_SECTIONS = DEFAULT_SECTIONS + ("powerlaw", "chunked", "sweep",
                                   "stream", "score", "re", "cd_fused",
                                   "serve", "mesh_stream", "tron")
DEFAULT_BUDGET_S = 840.0
DEFAULT_N, DEFAULT_D, DEFAULT_K = 1_000_000, 100_000, 30

# Out-of-core stream section shape: the chunk total must dwarf the
# host window (≥ 6×; 24/2 = 12×) for the RSS bound to be a real claim
# — and finer chunks tighten the spilled arm's floor (window, prefetch
# queue, and in-flight temporaries all scale with CHUNK size, the
# resident arm with the DATASET).
STREAM_CHUNKS = 24
STREAM_WINDOW = 2
STREAM_DEPTH = 2
STREAM_SWEEPS = 5

# Scoring section shape (ISSUE 4): same window-vs-dataset discipline as
# the stream section — the streamed arm's score chunks must dwarf the
# LRU host window for the bounded-RSS claim to mean anything.
SCORE_CHUNKS = 16
SCORE_WINDOW = 2
SCORE_DEPTH = 2
SCORE_PASSES = 3
SCORE_D_RE = 4

# Streamed-RE section shape (ISSUE 5): entity chunks must dwarf the LRU
# host window (same discipline as the stream/score sections), and the
# per-entity offset schedule decays at entity-specific rates so the
# converged-entity retirement curve is GRADUAL — entities cross the
# movement tolerance on different sweeps, the shape a converging CD
# endgame actually produces.
RE_CHUNKS = 24          # target entity chunks (window 2 → 12×)
RE_WINDOW = 2
RE_DEPTH = 2
RE_SWEEPS = 8
RE_D = 8                # dense RE feature width
RE_TOL = 1e-4           # solver tolerance = retirement threshold

# λ-sweep section shape: lanes × solver-iteration cap (kept static so
# the batched and sequential arms solve the identical problem set).
SWEEP_LANES = 6
SWEEP_MAX_ITERS = 12

# Fused-CD section shape (ISSUE 11): the SAME fixed-effect + random-
# effect workload trained twice — per-coordinate (C streamed passes per
# CD cycle: solver iterations × line-search trials per coordinate) and
# fused (ONE pass per cycle, Jacobi solves).  The fused arm runs more
# (cheap) cycles — its per-cycle step is one damped Newton update, not
# a full inner solve — so the section's claims are pass COUNT per
# cycle, per-pass time, peak RSS, and cross-arm coefficient parity at
# convergence, not equal-cycle wall clock.
CDF_CHUNKS = 8
CDF_WINDOW = 2
CDF_DEPTH = 2
CDF_FUSED_CYCLES = 40
CDF_LEGACY_ITERS = 4
CDF_LEGACY_MAX_ITERS = 15
CDF_D_RE = 4

# Multi-host mesh-stream section shape (ISSUE 16): MESH_HOSTS worker
# processes chunk-synchronized over one shared chunk grid — each host
# streams only its contiguous shard (4 of 12 chunks) from a per-host
# spill subdir and the per-chunk partials cross hosts once per chunk
# step.  The shard must still dwarf the host window (4/2 = 2× per
# host, 12/2 = 6× fleet-wide) so per-host RSS stays a real claim, and
# the fused cycle count is small: the section measures the fleet
# schedule (barrier wait, reduces, replicated odometer), not
# convergence endurance.
MESH_HOSTS = 3
MESH_CHUNKS = 12
MESH_WINDOW = 2
MESH_DEPTH = 2
MESH_CYCLES = 10

# Streaming TRON section shape (ISSUE 17): the SAME ill-conditioned
# chunked logistic problem solved twice to the SAME relative gradient
# tolerance — streamed trust-region Newton (chunk-accumulated HVPs,
# Jacobi-preconditioned Steihaug-CG) vs streamed L-BFGS — each arm in
# its own subprocess for honest per-arm RSS.  Ill-conditioning comes
# from power-law per-column feature scales spanning TRON_SCALE_DECADES
# decades: the Hessian diagonal then spans ~2×decades decades, which a
# diagonal-preconditioned Newton absorbs into its change of variables
# while limited-memory quasi-Newton pays for it in data passes — the
# pass-count gap IS the section's claim.  The chunk grid keeps the
# store-bounded discipline of the stream section (chunks ≥ 4× the host
# window) so the HVP pass's RSS story is a real out-of-core claim.
TRON_CHUNKS = 8
TRON_WINDOW = 2
TRON_DEPTH = 2
TRON_SCALE_DECADES = 2.5   # per-column scale span 10^0 .. 10^-2.5
TRON_L2 = 0.1              # small enough that the scale span survives
TRON_TOL = 1e-5            # shared relative gradient tolerance
TRON_MAX_ITERS = 500       # generous cap: L-BFGS must REACH tol

# Serve section shape (ISSUE 12): a subprocess-isolated model server
# (honest per-process RSS, real socket path) under SERVE_CLIENTS
# concurrent OPEN-LOOP clients — each fires on its own fixed schedule
# regardless of completions, so queueing delay lands IN the measured
# latency instead of throttling the offered load (the closed-loop
# trap).  The request pool replays real dataset rows with every 7th
# entity id remapped to an unseen one (the fixed-effect fallback path
# stays on the measured path).
SERVE_CLIENTS = 4
SERVE_ROWS_PER_REQ = 8
SERVE_REQS_PER_CLIENT = 100      # measured requests per client
SERVE_WARM_REQS = 8              # per client, before the clock starts
SERVE_INTERVAL_S = 0.010         # open-loop firing cadence per client
SERVE_POOL = 512                 # distinct request rows replayed
SERVE_BATCH_ROWS = 64            # largest micro-batch bucket
# Request tracing (ISSUE 14): the ON arm's tail threshold — low enough
# that the storm's queueing tail samples richly, high enough that the
# steady-state p50 request is dropped after its histogram folds.
SERVE_TRACE_THRESHOLD_MS = 25.0
# Closed-loop PAIRS for the tracing-overhead A/B: ONE request in
# flight alternating between the live off/on servers, so p50 is the
# request SERVICE time and each pair shares one instant of box state.
# The open-loop storm offers more load than a 2-core box sustains —
# its p50 is queue depth, which amplifies any delta and measures
# nothing about tracing.
SERVE_CLOSED_REQS = 600

# Fleet arm (ISSUE 13): supervisor + 2 replicas behind the frontend,
# one replica SIGKILLed mid-storm.  Claims under test: zero failed
# client requests (the frontend's bounded retry-once), the killed
# replica restarted + re-warmed + back in rotation with the
# supervisor-measured restart latency, and overload sheds (if any)
# reported as a fraction, not hidden.
SERVE_FLEET_REPLICAS = 2
SERVE_FLEET_REQS_PER_CLIENT = 300
SERVE_FLEET_INTERVAL_S = 0.020   # open-loop cadence (storm ~6 s)
SERVE_FLEET_KILL_FRACTION = 0.33  # SIGKILL one replica this far in

# Per-section wall-clock estimates at the FULL bench shape on the
# measured host (BENCH_r05 tail: etl 123 s, grr measure 346 s, colmajor
# 305 s, segment_sum 35 s; powerlaw/chunked from the r05 PERF record),
# linearly scaled by nnz for smaller shapes.  Pessimistic on purpose:
# a skipped section costs one number, a blown budget costs the whole
# record.
SECTION_EST_S = {
    "etl": 160.0,
    "cached": 45.0,
    "grr": 370.0,
    "segment_sum": 50.0,
    "colmajor": 330.0,
    "powerlaw": 500.0,
    "chunked": 300.0,
    # L+1 streamed solves over 4 ELL chunks (~(L·⌀16 + ~25) passes at
    # ~1.5 s/pass at the full shape) + chunk ETL.
    "sweep": 420.0,
    # Two chunk ETLs (one spilling to disk) + 2×(1 warm + STREAM_SWEEPS
    # timed) full-data passes.
    "stream": 420.0,
    # Two subprocess arms × (score-chunk ETL + 1 warm + SCORE_PASSES
    # timed one-pass scores).
    "score": 300.0,
    # Two subprocess arms × (entity-chunk ETL + RE_SWEEPS vmapped
    # bucket solves over the full dataset).
    "re": 420.0,
    # Two subprocess arms × (chunk ETL + a warm-up fit + the measured
    # fit: CDF_FUSED_CYCLES+1 passes fused, ~C×iters passes legacy).
    "cd_fused": 480.0,
    # TWO server subprocess arms (tracing off/on A/B — model load +
    # bucket warm-up each) + the open-loop client storm per arm
    # (~CLIENTS × REQS × INTERVAL of wall) + the parent's parity pass
    # over the request pool, then the fleet arm: 2 replica warm-ups, a
    # ~6 s storm with a mid-run SIGKILL, the restart-latency wait, and
    # the serve-report cross-process trace join.
    "serve": 480.0,
    # MESH_HOSTS concurrent worker subprocesses on a small box: each
    # pays the jax import + fused-program compile + full-dataset build,
    # then MESH_CYCLES chunk-synchronized fused passes over 1/HOSTS of
    # the chunks (the passes themselves are ~1/HOSTS of a cd_fused
    # pass, but the fixed per-worker costs dominate at bench shapes).
    "mesh_stream": 480.0,
    # Two subprocess arms × (chunk ETL + a short warm solve + the
    # measured solve-to-tolerance: tens of streamed passes TRON,
    # potentially hundreds L-BFGS on the ill-conditioned shape).
    "tron": 480.0,
}


def _peak_rss_mb() -> float:
    """Process high-water RSS (ru_maxrss is KB on Linux, bytes on mac)."""
    import resource

    peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    if sys.platform == "darwin":
        peak //= 1024
    return peak / 1024.0


def _current_rss_mb(field: str = "VmRSS") -> float | None:
    """Instantaneous RSS from /proc (Linux); None elsewhere.
    ``field="RssAnon"`` reads the anonymous-only portion — the
    spilled chunk window and its device aliases are FILE-backed
    (memory-mapped, reclaimable under pressure), so anon RSS is the
    honest can-this-OOM number for the out-of-core arm."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith(field + ":"):
                    return int(line.split()[1]) / 1024.0
    except OSError:
        pass
    return None


class _RssSampler:
    """Peak CURRENT RSS over a window, sampled at ~50 Hz — unlike
    ru_maxrss (a process-lifetime high-water mark) this attributes a
    peak to ONE bench arm, which is what the spilled-vs-resident
    comparison needs.  Falls back to ru_maxrss when /proc is absent."""

    def __init__(self):
        import threading

        self._stop = threading.Event()
        self._peak = 0.0
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while not self._stop.is_set():
            cur = _current_rss_mb()
            if cur is not None:
                self._peak = max(self._peak, cur)
            self._stop.wait(0.02)

    def __enter__(self):
        cur = _current_rss_mb()
        if cur is not None:
            self._peak = cur
            self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join()
        return False

    @property
    def peak_mb(self) -> float:
        return self._peak if self._peak else _peak_rss_mb()


def _make_ell(n: int, d: int, k: int, seed: int = 0):
    """Vectorized synthetic ELL batch: unique col ids per row by
    stratified sampling (one column per d/k-wide block)."""
    rng = np.random.default_rng(seed)
    block = max(d // k, 1)
    cols = (np.arange(k, dtype=np.int64) * block)[None, :] + rng.integers(
        0, block, (n, k)
    )
    cols = np.minimum(cols, d - 1)
    vals = rng.normal(0, 1, (n, k)).astype(np.float32)
    labels = (rng.uniform(size=n) < 0.5).astype(np.float32)
    return cols.astype(np.int32), vals, labels


def _grr_stream_bytes(pair) -> int:
    """Bytes the GRR plan actually moves per fused value+gradient step:
    both directions' (vals f32 + 3 route planes i8) streams — including
    each direction's second-level overflow plan — spill COO, table
    windows, and the dense hot side."""

    def direction_bytes(d_) -> int:
        from photon_ml_tpu.data.grr import GrrRangeSplit

        if isinstance(d_, GrrRangeSplit):
            return sum(direction_bytes(p) for p in d_.parts)
        slots = d_.n_supertiles * 16384
        b = slots * (4 + 3)                           # vals + g1/g2/g3
        b += d_.n_spill * 12                          # spill idx/seg/val
        if d_.dense_grid:
            # gw-major grid: the window block index only changes between
            # gw runs, so each [128,128] window streams ONCE per run;
            # the per-tile partials are written then re-read by the
            # reshape-sum reduction.
            b += d_.n_gw * 16384 * 4
            b += 2 * d_.n_supertiles * (16384 // d_.cap) * 4
        else:
            # Legacy order: one window is (re)streamed per supertile.
            b += d_.n_supertiles * 16384 * 4
        if d_.overflow is not None:
            b += direction_bytes(d_.overflow)
        return b

    total = direction_bytes(pair.row_dir) + direction_bytes(pair.col_dir)
    if pair.col_mid is not None:
        total += direction_bytes(pair.col_mid)
    total += int(np.prod(pair.x_hot.shape)) * 4 * 2   # dense side, 2 dirs
    return total


class BenchContext:
    """Shared state across sections: data, plans, step fn, budget."""

    def __init__(self, args):
        self.n, self.d, self.k = args.n, args.d, args.k
        self.cache_dir = args.cache_dir
        self.no_compile_cache = args.no_compile_cache
        self.guards = args.guards
        self.monitor = getattr(args, "monitor", False)
        self.deadline = time.time() + args.budget_s
        self.budget_s = args.budget_s
        self.record: dict = {}
        self.errors: dict = {}
        self.skipped: list = []
        self.step_times: dict = {}
        self._data = None
        self._plan_path = None
        self._pair = None
        self._cm = None
        self._step = None
        self._w0 = None
        self.scale = (self.n * self.k) / (DEFAULT_N * DEFAULT_K)

    def remaining(self) -> float:
        return self.deadline - time.time()

    def estimate(self, section: str) -> float:
        est = SECTION_EST_S[section] * self.scale
        if section in ("stream", "score", "re"):
            # Two subprocess arms pay a fixed jax-import + compile cost
            # each, regardless of shape.
            est += 60.0
        elif section == "mesh_stream":
            # MESH_HOSTS concurrent workers each pay the fixed
            # jax-import + compile cost (concurrent, but the box is
            # small — charge them near-serially).
            est += 40.0 * MESH_HOSTS
        # Sections that need the GRR plan pay a COLD build first when
        # neither a resident pair nor a cache file exists (e.g. etl was
        # skipped or never ran) — charge it, or a section admitted
        # under its own estimate blows the budget on the hidden build.
        if section == "cached" and not os.path.exists(self.plan_path()):
            est += SECTION_EST_S["etl"] * self.scale
        elif (section == "grr" and self._pair is None
                and not os.path.exists(self.plan_path())):
            est += SECTION_EST_S["etl"] * self.scale
        return max(3.0, est)

    # -- lazy shared pieces -------------------------------------------------

    def data(self):
        if self._data is None:
            self._data = _make_ell(self.n, self.d, self.k)
        return self._data

    def plan_path(self) -> str:
        # Defaults resolved from build_grr_pair's own signature — the
        # bench never holds a copy of them that could drift.  Memoized:
        # the fingerprint hashes the full dataset, and estimate()/
        # pair()/section_cached all ask for the same immutable answer.
        if self._plan_path is None:
            from photon_ml_tpu.data.grr import pair_cache_path_for

            cols, vals, _ = self.data()
            self._plan_path = pair_cache_path_for(
                cols, vals, self.d, self.cache_dir)
        return self._plan_path

    def pair(self):
        """The GRR plan — through the production warm path
        (``build_grr_pair`` with ``cache_dir``) when a cache file
        exists, else a cold build (recorded so later sections aren't
        double-charged)."""
        if self._pair is None:
            if os.path.exists(self.plan_path()):
                from photon_ml_tpu.data.grr import build_grr_pair

                cols, vals, _ = self.data()
                self._pair = build_grr_pair(cols, vals, self.d,
                                            cache_dir=self.cache_dir)
            else:
                self._pair = self._cold_build()
        return self._pair

    def _cold_build(self):
        """Cold plan build: never READS the cache (the ETL number of
        record stays honest) but saves the host plan for ``cached``
        (the save is timed inside ``build_grr_pair``'s phases)."""
        from photon_ml_tpu.data.grr import build_grr_pair

        cols, vals, _ = self.data()
        t0 = time.time()
        pair = build_grr_pair(cols, vals, self.d,
                              cache_dir=self.cache_dir,
                              cache_rebuild=True)
        self.record.setdefault("etl_grr_s", round(time.time() - t0, 1))
        self._pair = pair
        return pair

    def mk_batch(self, colmajor=None, grr=None):
        import jax.numpy as jnp

        from photon_ml_tpu.data.batch import SparseBatch

        cols, vals, labels = self.data()
        n = self.n
        return SparseBatch(
            values=jnp.asarray(vals), col_ids=jnp.asarray(cols),
            labels=jnp.asarray(labels),
            weights=jnp.ones((n,), jnp.float32),
            offsets=jnp.zeros((n,), jnp.float32),
            mask=jnp.ones((n,), jnp.float32),
            dim=self.d, colmajor=colmajor, grr=grr,
        )

    def step_fn(self):
        if self._step is None:
            import jax.numpy as jnp

            from photon_ml_tpu.data.normalization import (
                NormalizationContext,
            )
            from photon_ml_tpu.ops import losses
            from photon_ml_tpu.ops.objective import GLMObjective
            from photon_ml_tpu.ops.regularization import (
                RegularizationContext,
            )

            obj = GLMObjective(
                loss=losses.LOGISTIC,
                reg=RegularizationContext.l2(1.0),
                norm=NormalizationContext.identity(),
            )

            def step(w, batch):
                _, g = obj.value_and_gradient(w, batch)
                return w - 1e-6 * g

            self._step = step
            self._w0 = jnp.asarray(
                np.random.default_rng(1).normal(0, 0.1, self.d),
                jnp.float32)
        return self._step, self._w0

    def measure_variant(self, name: str, batch, length: int, iters: int):
        from photon_ml_tpu.utils.timing import measure_scanned

        step, w0 = self.step_fn()
        t0 = time.time()
        s = measure_scanned(step, w0, batch, length=length, iters=iters)
        self.step_times[name] = s
        print(f"{name}: {s*1e3:.2f} ms/step "
              f"(measured in {time.time()-t0:.0f}s)", file=sys.stderr)
        return s


# ---------------------------------------------------------------------------
# Sections.  Each mutates ctx.record; scan lengths amortize per-dispatch
# overhead to <~2% of step time for EVERY variant (advisor finding:
# unequal amortization biased the cross-variant ratio): the production
# solvers run the WHOLE optimize loop as one device program
# (lbfgs/tron while_loop), so per-call dispatch/fence is measurement
# artifact, not production cost — the axon tunnel costs ~100 ms per
# dispatch+fence round.  GRR at ~5 ms/step needs length 250;
# colmajor/segment_sum at ~500 ms/step reach the same <~1% bias at
# length 20.
# ---------------------------------------------------------------------------


def section_etl(ctx: BenchContext) -> None:
    """Cold plan ETL (never reads the cache — the number of record) +
    the colmajor build, with the plan persisted for ``cached``."""
    from photon_ml_tpu.data import grr as grr_mod
    from photon_ml_tpu.data.colmajor import build_colmajor

    ctx.record.pop("etl_grr_s", None)  # force a fresh cold measurement
    ctx._pair = None
    ctx._cold_build()
    ctx.record["etl_phases"] = {
        k_: round(v, 2) for k_, v in grr_mod.last_build_phases.items()}
    cols, vals, _ = ctx.data()
    t0 = time.time()
    ctx._cm = build_colmajor(cols, vals, ctx.d)
    ctx.record["etl_colmajor_s"] = round(time.time() - t0, 1)
    print(f"ETL: grr={ctx.record['etl_grr_s']}s "
          f"(phases {ctx.record['etl_phases']}) "
          f"colmajor={ctx.record['etl_colmajor_s']}s", file=sys.stderr)


def section_cached(ctx: BenchContext) -> None:
    """Warm-path ETL: plan-cache load + device transfer vs cold build.

    The cold reference comes from this process's ``etl`` section when
    it ran; otherwise one cold build is performed here (and saved), so
    the section is self-contained.  The warm number drives the REAL
    production path — ``build_grr_pair`` with ``cache_dir`` — and
    reads the load/transfer split from its own phase timings, so the
    bench can never measure a different warm protocol than runs take."""
    from photon_ml_tpu.data import grr

    path = ctx.plan_path()
    if not os.path.exists(path):
        ctx._cold_build()
    cold_s = ctx.record.get("etl_grr_s")

    cols, vals, _ = ctx.data()
    t0 = time.time()
    warm_pair = grr.build_grr_pair(cols, vals, ctx.d,
                                   cache_dir=ctx.cache_dir)
    warm_s = time.time() - t0
    ph = dict(grr.last_build_phases)
    if ph.get("cache_hit") != 1.0:
        raise RuntimeError(f"plan cache entry unreadable: {path}")
    load_s = ph.get("cache_load_s", 0.0)
    transfer_s = ph.get("transfer_fence_s", 0.0)

    parity = None
    if ctx._pair is not None:
        # Cheap correctness cross-check when both plans are resident:
        # one contraction each direction must agree to float tolerance.
        import jax

        w = jax.numpy.asarray(
            np.random.default_rng(7).normal(0, 1, ctx.d), np.float32)
        a = np.asarray(ctx._pair.dot(w))
        b = np.asarray(warm_pair.dot(w))
        parity = bool(np.allclose(a, b, rtol=1e-5, atol=1e-5))
    ctx._pair = warm_pair

    ctx.record["cached"] = {
        "etl_warm_s": round(warm_s, 2),
        "load_s": round(load_s, 2),
        "transfer_s": round(transfer_s, 2),
        "etl_cold_s": cold_s,
        "warm_speedup": (round(cold_s / warm_s, 1)
                         if cold_s and warm_s > 0 else None),
        "parity_ok": parity,
        "plan_file_mb": round(os.path.getsize(path) / 1e6, 1),
    }
    print(f"cached: warm ETL {warm_s:.2f}s (load {load_s:.2f} + "
          f"transfer {transfer_s:.2f}) vs cold {cold_s}s "
          f"-> {ctx.record['cached']['warm_speedup']}x", file=sys.stderr)


def section_grr(ctx: BenchContext) -> None:
    ctx.measure_variant("grr", ctx.mk_batch(grr=ctx.pair()), 250, 2)


def section_colmajor(ctx: BenchContext) -> None:
    if ctx._cm is None:
        from photon_ml_tpu.data.colmajor import build_colmajor

        cols, vals, _ = ctx.data()
        t0 = time.time()
        ctx._cm = build_colmajor(cols, vals, ctx.d)
        ctx.record.setdefault("etl_colmajor_s",
                              round(time.time() - t0, 1))
    ctx.measure_variant("colmajor", ctx.mk_batch(colmajor=ctx._cm), 20, 2)


def section_segment_sum(ctx: BenchContext) -> None:
    ctx.measure_variant("segment_sum", ctx.mk_batch(), 20, 2)


def section_powerlaw(ctx: BenchContext) -> None:
    """Power-law-columns variant (round-4 verdict item #1: the uniform
    bench hides exactly the skew defect the column-range split fixes).
    Reciprocal popularity P(col) ∝ 1/(col+x0) puts ~45% of entries in
    table window 0 at this shape — the KDD/CTR profile."""
    from photon_ml_tpu.data.grr import build_grr_pair

    n, d, k = ctx.n, ctx.d, ctx.k
    _, vals, _ = ctx.data()
    rng = np.random.default_rng(3)
    x0 = float(d) / 14.0
    u = rng.uniform(size=(n, k))
    cols_p = np.minimum(x0 * np.exp(u * np.log((d + x0) / x0)) - x0,
                        d - 1).astype(np.int32)
    t0 = time.time()
    pair_p = build_grr_pair(cols_p, vals, d)
    etl_s = time.time() - t0
    row_stats = pair_p.row_dir.plan_stats()
    t0 = time.time()
    t_grr_p = ctx.measure_variant("grr_powerlaw",
                                  ctx.mk_batch(grr=pair_p), 250, 2)
    print(f"grr powerlaw: row spill_frac={row_stats['spill_frac']:.4f} "
          f"coo_frac={row_stats['coo_frac']:.5f} "
          f"caps={row_stats['cap']}", file=sys.stderr)
    ctx.record["powerlaw"] = {
        "step_ms_grr": round(t_grr_p * 1e3, 3),
        "etl_grr_s": round(etl_s, 1),
        "row_spill_frac": round(row_stats["spill_frac"], 4),
        "row_coo_frac": round(row_stats["coo_frac"], 5),
        "row_caps": row_stats["cap"],
        "range_bounds": row_stats.get("bounds"),
    }


def section_chunked(ctx: BenchContext) -> None:
    """Chunked (beyond-HBM) regime: one full-dataset value+gradient pass
    through resident ELL chunks (data/chunked_batch.py +
    optim/streaming.py) — the class that trains 3x10^7 examples on
    one chip (PERF.md).  Timed EAGERLY including per-chunk dispatch,
    because that IS this class's production cost (the streaming
    solver cannot fuse the pass into one device program)."""
    import jax

    from photon_ml_tpu.data.chunked_batch import build_chunked_batch
    from photon_ml_tpu.data.normalization import NormalizationContext
    from photon_ml_tpu.data.sparse_rows import SparseRows
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.ops.objective import GLMObjective
    from photon_ml_tpu.ops.regularization import RegularizationContext
    from photon_ml_tpu.optim.streaming import ChunkedGLMObjective

    cols, vals, labels = ctx.data()
    n, d, k = ctx.n, ctx.d, ctx.k
    obj = GLMObjective(
        loss=losses.LOGISTIC,
        reg=RegularizationContext.l2(1.0),
        norm=NormalizationContext.identity(),
    )
    _, w0 = ctx.step_fn()
    t0 = time.time()
    rows_sp = SparseRows.from_flat(
        np.arange(n + 1, dtype=np.int64) * k,
        cols.reshape(-1).astype(np.int64), vals.reshape(-1))
    cobj = ChunkedGLMObjective(
        obj, build_chunked_batch(rows_sp, d, labels, n_chunks=4,
                                 layout="ell"),
        max_resident=4)
    etl_chunked_s = time.time() - t0
    jax.block_until_ready(cobj.value_and_gradient(w0)[1])  # compile+place
    t0 = time.time()
    chunk_iters = 5
    for _ in range(chunk_iters):
        # Fence EVERY pass: the streaming solver syncs after each
        # evaluation (the line search reads the value on host), so a
        # per-pass fence is production cost, not artifact.
        jax.block_until_ready(cobj.value_and_gradient(w0)[1])
    t_pass = (time.time() - t0) / chunk_iters
    print(f"chunked (4 ELL chunks, fully resident): {t_pass*1e3:.1f} "
          f"ms/pass (etl {etl_chunked_s:.0f}s)", file=sys.stderr)
    ctx.record["chunked"] = {
        "pass_ms": round(t_pass * 1e3, 1),
        "examples_per_sec": round(n / t_pass, 1),
        "n_chunks": 4,
        # All chunks held in HBM across passes — the resident end of
        # the chunked regime (no per-pass transfer timed); streaming
        # re-placement costs are link-dependent (PERF.md).
        "max_resident": 4,
        "regime": "resident",
        "layout": "ell",
        "etl_s": round(etl_chunked_s, 1),
    }


def section_sweep(ctx: BenchContext) -> None:
    """Batched λ-sweep vs L× sequential fits (ISSUE 2 tentpole
    measurement): the same L-point L2 grid over the chunked objective,
    trained once as ONE swept masked-lane solve (one chunk stream feeds
    all L coefficient lanes per evaluation) and once as L sequential
    streaming fits.  Records wall time, data passes (full chunk
    sweeps), and passes per grid step — the L → 1 amortization —
    plus a batched-vs-sequential coefficient parity check."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.data.chunked_batch import build_chunked_batch
    from photon_ml_tpu.data.normalization import NormalizationContext
    from photon_ml_tpu.data.sparse_rows import SparseRows
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.ops.objective import GLMObjective
    from photon_ml_tpu.ops.regularization import (
        RegularizationContext,
        RegularizationType,
        SweptRegularization,
    )
    from photon_ml_tpu.optim import OptimizerConfig
    from photon_ml_tpu.optim.streaming import (
        ChunkedGLMObjective,
        streaming_lbfgs_solve,
        streaming_lbfgs_solve_swept,
    )

    cols, vals, labels = ctx.data()
    n, d, k = ctx.n, ctx.d, ctx.k
    L = SWEEP_LANES
    lams = [float(10.0 ** e) for e in np.linspace(1.0, -2.0, L)]
    cfg = OptimizerConfig(max_iters=SWEEP_MAX_ITERS, tolerance=1e-6)

    t0 = time.time()
    rows_sp = SparseRows.from_flat(
        np.arange(n + 1, dtype=np.int64) * k,
        cols.reshape(-1).astype(np.int64), vals.reshape(-1))
    cb = build_chunked_batch(rows_sp, d, labels, n_chunks=4,
                             layout="ell")
    etl_s = time.time() - t0

    def mk_obj(lam):
        return GLMObjective(
            loss=losses.LOGISTIC,
            reg=RegularizationContext.l2(lam),
            norm=NormalizationContext.identity(),
        )

    w0 = jnp.zeros((d,), jnp.float32)

    # --- batched: one swept solve, all L lanes per data pass ---------
    reg = SweptRegularization.from_grid(RegularizationType.L2, lams)
    cobj_b = ChunkedGLMObjective(mk_obj(1.0), cb, max_resident=4)
    W0 = jnp.zeros((L, d), jnp.float32)
    # Warm both arms' compiles before timing (one max_iters=1 solve
    # each) — the bench convention everywhere: compiles are one-time
    # (and cached persistently), not per-grid cost.
    t0 = time.time()
    warm_cfg = OptimizerConfig(max_iters=1, tolerance=1e-6)
    streaming_lbfgs_solve_swept(
        lambda W: cobj_b.value_and_gradient_swept(W, reg),
        lambda W: cobj_b.value_swept(W, reg),
        W0, warm_cfg)
    # The 1-iteration warm solve only exercises the value-only program
    # if it happens to backtrack — compile it explicitly so a timed
    # iteration's first backtrack can't pay the XLA compile.
    cobj_b.value_swept(W0, reg)
    co_w = ChunkedGLMObjective(mk_obj(1.0), cb, max_resident=4)
    streaming_lbfgs_solve(co_w.value_and_gradient, w0, warm_cfg,
                          value_fn=co_w.value)
    co_w.value(w0)
    compile_s = time.time() - t0
    cobj_b.sweeps = 0
    t0 = time.time()
    res_b = streaming_lbfgs_solve_swept(
        lambda W: cobj_b.value_and_gradient_swept(W, reg),
        lambda W: cobj_b.value_swept(W, reg),
        W0, cfg)
    jax.block_until_ready(res_b.w)
    batched_s = time.time() - t0
    passes_b = cobj_b.sweeps
    iters_b = int(jnp.max(res_b.iterations))          # grid steps
    lane_iters_b = int(jnp.sum(res_b.iterations))
    print(f"sweep batched: {batched_s:.1f}s, {passes_b} data passes, "
          f"{iters_b} grid steps ({lane_iters_b} lane-iterations)",
          file=sys.stderr)

    # --- sequential: L independent streaming fits --------------------
    seq_s = 0.0
    passes_s = 0
    iters_s = 0
    W_seq = []
    for lam in lams:
        co = ChunkedGLMObjective(mk_obj(lam), cb, max_resident=4)
        t0 = time.time()
        r = streaming_lbfgs_solve(co.value_and_gradient, w0, cfg,
                                  value_fn=co.value)
        jax.block_until_ready(r.w)
        seq_s += time.time() - t0
        passes_s += co.sweeps
        iters_s += int(r.iterations)
        W_seq.append(np.asarray(r.w))
    print(f"sweep sequential: {seq_s:.1f}s, {passes_s} data passes, "
          f"{iters_s} lane-iterations", file=sys.stderr)

    parity = float(np.max(np.abs(np.asarray(res_b.w) - np.stack(W_seq))))
    # Passes per grid step (one iteration of EVERY lane): sequential
    # pays ~L fits' worth; batched pays ~1-2 shared sweeps.
    per_step_b = passes_b / max(iters_b, 1)
    per_step_s = (passes_s / max(iters_s, 1)) * L
    ctx.record["sweep"] = {
        "lanes": L,
        "max_iters": SWEEP_MAX_ITERS,
        "batched_s": round(batched_s, 2),
        "sequential_s": round(seq_s, 2),
        "speedup": (round(seq_s / batched_s, 2) if batched_s > 0
                    else None),
        "etl_chunked_s": round(etl_s, 1),
        "compile_s": round(compile_s, 1),
        "parity_max_dw": parity,
        "phases": {
            "batched": {
                "data_passes": passes_b,
                "grid_steps": iters_b,
                "lane_iterations": lane_iters_b,
                "passes_per_grid_step": round(per_step_b, 2),
            },
            "sequential": {
                "data_passes": passes_s,
                "lane_iterations": iters_s,
                "passes_per_grid_step": round(per_step_s, 2),
            },
        },
        "pass_amortization": (round(per_step_s / per_step_b, 2)
                              if per_step_b > 0 else None),
    }
    print(f"sweep: batched {batched_s:.1f}s vs sequential {seq_s:.1f}s "
          f"-> {ctx.record['sweep']['speedup']}x; passes/grid-step "
          f"{per_step_s:.1f} -> {per_step_b:.1f}", file=sys.stderr)


def _telemetry_block(summary: dict, sweeps_key: str = "solver.sweeps") -> dict:
    """The bench-facing slice of a telemetry summary (ISSUE 7): the
    overlap/stall derivations plus the pinned counters, embedded in
    each arm's JSON record so a section's last line carries the
    pipeline story alongside the wall-clock one."""
    c = summary.get("counters", {})
    d = summary.get("derived", {})
    return {
        "overlap_efficiency": d.get("overlap_efficiency"),
        "consumer_blocked_fraction": d.get("consumer_blocked_fraction"),
        "producer_stall_fraction": d.get("producer_stall_fraction"),
        "consumer_wait_s": round(c.get("prefetch.consumer_wait_s", 0.0), 3),
        "producer_stall_s": round(c.get("prefetch.producer_stall_s", 0.0), 3),
        "pass_span_total_s": d.get("pass_span_total_s"),
        "sweeps": c.get(sweeps_key, 0),
        "store_hits": c.get("store.hits", 0),
        "store_loads": c.get("store.loads", 0),
        "compiles": c.get("jax.compiles", 0),
        # Live-monitor event counters (ISSUE 10): the monitoring-off
        # default must read 0 here — the contract test pins it.
        "progress_events": c.get("monitor.progress_events", 0),
        "alerts": c.get("monitor.alerts", 0),
        # Captured XLA program costs (ISSUE 8): whatever the arm's
        # instrumented paths resolved during the telemetry window.
        "device_cost": summary.get("device", {}).get("programs") or None,
    }


def stream_arm_main(args) -> int:
    """One arm of the ``stream`` section, run in its OWN process
    (``bench.py --stream-arm spilled|resident``): a shared process
    would let the first arm's freed glibc arenas absorb the second
    arm's allocations and understate its RSS — per-arm ``ru_maxrss``
    is the honest high-water mark.  Emits one JSON line (the section
    contract, one level down) and writes the final gradient next to
    the cache dir for the parent's cross-arm parity check."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.data.chunked_batch import build_chunked_batch
    from photon_ml_tpu.data.normalization import NormalizationContext
    from photon_ml_tpu.data.sparse_rows import SparseRows
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.ops.objective import GLMObjective
    from photon_ml_tpu.ops.regularization import RegularizationContext
    from photon_ml_tpu.optim.streaming import ChunkedGLMObjective

    arm = args.stream_arm
    n, d, k = args.n, args.d, args.k
    cols, vals, labels = _make_ell(n, d, k)
    rows_sp = SparseRows.from_flat(
        np.arange(n + 1, dtype=np.int64) * k,
        cols.reshape(-1).astype(np.int64), vals.reshape(-1))
    obj = GLMObjective(
        loss=losses.LOGISTIC,
        reg=RegularizationContext.l2(1.0),
        norm=NormalizationContext.identity(),
    )
    w0 = jnp.asarray(
        np.random.default_rng(1).normal(0, 0.1, d), jnp.float32)
    base_mb = _current_rss_mb()   # raw data + runtime, pre-chunk-ETL
    base_anon_mb = _current_rss_mb("RssAnon")

    t0 = time.time()
    if arm == "spilled":
        cb = build_chunked_batch(
            rows_sp, d, labels, n_chunks=STREAM_CHUNKS, layout="ell",
            spill_dir=os.path.join(args.cache_dir, "spill"),
            host_max_resident=STREAM_WINDOW)
        cobj = ChunkedGLMObjective(obj, cb, max_resident=0,
                                   prefetch_depth=STREAM_DEPTH)
    else:
        cb = build_chunked_batch(rows_sp, d, labels,
                                 n_chunks=STREAM_CHUNKS, layout="ell")
        cobj = ChunkedGLMObjective(obj, cb,
                                   max_resident=STREAM_CHUNKS)
    etl_s = time.time() - t0
    jax.block_until_ready(cobj.value_and_gradient(w0)[1])   # compile
    times = []
    # Steady-state RSS is sampled over the TIMED sweeps only:
    # ru_maxrss spans the whole arm and the one-time XLA compile spike
    # can set both arms' high-water, masking the training-regime
    # difference the section exists to measure.
    g = None
    # --guards: the timed sweeps run under the runtime guard harness —
    # the steady-state contract is ZERO compiles (everything compiled
    # in the warmup above; a nonzero count means a per-sweep retrace)
    # and no implicit host<->device transfers in the per-chunk dispatch
    # loop (transfer_guard 'log': reported, not fatal — on the CPU
    # backend the guard is structurally silent, host == device).
    # Telemetry over the TIMED sweeps only (metrics mode): the arm's
    # JSON gains the prefetcher overlap-efficiency block — how much of
    # the disk+staging tier the pipeline hid under device compute.
    # Started BEFORE the guard contexts and closed after they exit, so
    # the two jax.log_compiles scopes nest properly.
    from photon_ml_tpu import telemetry

    tel = telemetry.start("metrics")
    # --monitor (ISSUE 10): the live monitor — snapshot throttling,
    # online alert evaluation, AND the ephemeral /status endpoint
    # thread — spans the timed sweeps, so the pass_ms delta vs an
    # unmonitored arm IS the monitoring overhead the ≤2% acceptance
    # budget gates.  Off stays the default: no monitor session, no
    # status thread, zero `progress` events (the contract test pins
    # both states).
    mon = None
    if args.monitor:
        from photon_ml_tpu.telemetry import monitor as _mon

        mon = _mon.start(status_port=0)
    # Device cost (ISSUE 8) rides the IN-SWEEP capture on the first
    # timed pass: it reuses the chunk that pass already loaded (an
    # explicit pre-capture here would bump store.hits/loads with an
    # access the timed sweeps never made), emits no "Compiling" record
    # (lowering cache → the --guards zero-compile contract holds), and
    # its one-time AOT relower lands in a single pass that the
    # median-of-5 timing excludes.
    guard_stack = ExitStack()
    compile_log = None
    if args.guards:
        from photon_ml_tpu.analysis.guards import (
            count_compiles,
            no_implicit_transfers,
        )

        compile_log = guard_stack.enter_context(count_compiles())
        guard_stack.enter_context(no_implicit_transfers("log"))
    with guard_stack, _RssSampler() as rss:
        for _ in range(STREAM_SWEEPS):
            # Fence every pass — the streaming solver syncs per
            # evaluation (the line search reads the value on host).
            t0 = time.time()
            g = cobj.value_and_gradient(w0)[1]
            jax.block_until_ready(g)
            times.append(time.time() - t0)
    progress_block = None
    status_ok = None
    if mon is not None:
        # Prove the endpoint is live from inside the measured arm: one
        # localhost GET against the ephemeral port, parsed as JSON.
        import urllib.request

        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{mon.status_port}/status",
                    timeout=5) as resp:
                status_ok = bool(json.load(resp).get("stages"))
        except OSError as e:
            status_ok = False
            print(f"status endpoint probe failed: {e}", file=sys.stderr)
        progress_block = mon.summary()
        mon.close()
    tel_summary = tel.summary()
    tel.close()
    # Median, not mean: single passes on a small shared host jitter
    # ±20% and one descheduled pass would swing the cross-arm ratio.
    pass_s = float(np.median(times))
    # The last timed sweep's gradient IS the parity artifact — no
    # extra data pass to capture it.
    g = np.asarray(g)
    np.save(os.path.join(args.cache_dir, f"stream_grad_{arm}.npy"), g)

    peak = _peak_rss_mb()
    sweep_peak = rss.peak_mb
    anon = _current_rss_mb("RssAnon")   # steady state; None pre-4.5
    rec = {
        "arm": arm,
        "etl_s": round(etl_s, 1),
        "pass_ms": round(pass_s * 1e3, 1),
        "pass_ms_all": [round(t * 1e3, 1) for t in times],
        "examples_per_sec": round(n / pass_s, 1),
        "peak_rss_mb": round(peak, 1),
        "sweep_peak_rss_mb": round(sweep_peak, 1),
        # RSS attributable to the chunk tier at steady state: the
        # sweep-window peak minus the raw-data baseline snapshotted
        # before the chunk build.
        "rss_delta_mb": (round(sweep_peak - base_mb, 1)
                         if base_mb is not None else None),
        # Anonymous-only growth (kernel >= 4.5): the spilled arm's
        # window and device aliases are file-backed (reclaimable), so
        # this is the can-this-OOM working set.
        "anon_delta_mb": (round(anon - base_anon_mb, 1)
                          if anon is not None
                          and base_anon_mb is not None else None),
        "telemetry": _telemetry_block(tel_summary),
        # The per-chunk value+gradient program's XLA cost analysis +
        # roofline estimate (ISSUE 8 acceptance: FLOPs, bytes, and the
        # analytic time floor ride the arm's JSON).
        "device_cost": tel_summary.get("device", {}).get(
            "programs", {}).get("chunk_vg"),
    }
    if progress_block is not None:
        # The monitoring-on contract: stage snapshots + alerts + the
        # endpoint probe ride the arm's JSON.
        rec["progress"] = progress_block
        rec["status_ok"] = status_ok
    if compile_log is not None:
        rec["guards"] = {
            # Steady-state sweeps must compile nothing; a retrace here
            # is exactly the regression the budget tests pin.
            "sweep_compiles": compile_log.count,
            "sweep_compile_programs": sorted(set(compile_log.programs)),
            "transfer_guard": "log",
        }
    if arm == "spilled":
        store = cb.store
        rec.update({
            "peak_live_chunks": store.peak_resident,
            "disk_loads": store.loads,
            "window_hits": store.hits,
            "spill_files_mb": round(sum(
                os.path.getsize(store.path(i))
                for i in range(STREAM_CHUNKS) if store.has(i)) / 1e6, 1),
        })
    print(json.dumps(rec))
    return 0


def section_stream(ctx: BenchContext) -> None:
    """Out-of-core streaming regime (ISSUE 3 tentpole measurement):
    the SAME full-data value+gradient sweeps run twice — once with the
    disk-backed chunk store (``spill_dir``, host window
    ``STREAM_WINDOW`` of ``STREAM_CHUNKS`` chunks, async
    disk→host→device prefetch) and once all-resident — each arm in
    its own subprocess so peak host RSS is measured per arm
    (``ru_maxrss``; one shared process would hide the second arm's
    growth in the first arm's freed allocator arenas).  The claims
    under test: host RSS bounded by the window (chunks total 6× the
    window at this section's shape) while wall-clock per sweep stays
    within ~1.3× of all-resident (prefetch hides the disk tier)."""
    import shutil
    import subprocess

    spill_dir = os.path.join(ctx.cache_dir, "spill")
    shutil.rmtree(spill_dir, ignore_errors=True)  # honest cold spill ETL

    def run_arm(arm: str) -> dict:
        t0 = time.time()
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--stream-arm", arm, "--n", str(ctx.n), "--d", str(ctx.d),
             "--k", str(ctx.k), "--cache-dir", ctx.cache_dir]
            + (["--no-compile-cache"] if ctx.no_compile_cache else [])
            + (["--guards"] if ctx.guards else [])
            + (["--monitor"] if ctx.monitor else []),
            capture_output=True, text=True,
            timeout=max(60.0, ctx.remaining()),
        )
        sys.stderr.write(proc.stderr)
        if proc.returncode != 0:
            raise RuntimeError(f"stream arm {arm!r} failed "
                               f"(rc={proc.returncode}): "
                               f"{proc.stderr[-500:]}")
        rec = json.loads(
            [ln for ln in proc.stdout.splitlines() if ln.strip()][-1])
        rec["arm_wall_s"] = round(time.time() - t0, 1)
        return rec

    spilled = run_arm("spilled")
    resident = run_arm("resident")
    g_s = np.load(os.path.join(ctx.cache_dir, "stream_grad_spilled.npy"))
    g_r = np.load(os.path.join(ctx.cache_dir,
                               "stream_grad_resident.npy"))
    parity = float(np.max(np.abs(g_s - g_r)))

    def ratio(a, b):
        # Explicit None/zero-divisor guard: a legitimate 0.0 numerator
        # (a flat arm) must report 0.0, not null.
        if a is None or b is None or b == 0:
            return None
        return round(a / b, 2)

    ctx.record["stream"] = {
        "n_chunks": STREAM_CHUNKS,
        "host_max_resident": STREAM_WINDOW,
        "prefetch_depth": STREAM_DEPTH,
        "sweeps_timed": STREAM_SWEEPS,
        "layout": "ell",
        "monitor": ctx.monitor,
        "spilled": spilled,
        "resident": resident,
        # The two acceptance numbers: how much smaller the spilled
        # arm's training working set is (chunk-tier RSS growth over
        # the shared raw-data baseline), and the wall-clock cost of
        # streaming from disk.
        "rss_delta_ratio": ratio(resident["rss_delta_mb"],
                                 spilled["rss_delta_mb"]),
        "anon_delta_ratio": ratio(resident["anon_delta_mb"],
                                  spilled["anon_delta_mb"]),
        "peak_rss_ratio": ratio(resident["peak_rss_mb"],
                                spilled["peak_rss_mb"]),
        "pass_time_ratio": ratio(spilled["pass_ms"],
                                 resident["pass_ms"]),
        "grad_parity_max": parity,
    }
    s = ctx.record["stream"]
    print(f"stream: spilled {spilled['pass_ms']} ms/pass (peak RSS "
          f"{spilled['peak_rss_mb']} MB, Δ{spilled['rss_delta_mb']} MB,"
          f" window {spilled['peak_live_chunks']}/{STREAM_CHUNKS} "
          f"chunks) vs resident {resident['pass_ms']} ms/pass (peak "
          f"{resident['peak_rss_mb']} MB, Δ{resident['rss_delta_mb']} "
          f"MB); time ratio {s['pass_time_ratio']}x, RSS-delta ratio "
          f"{s['rss_delta_ratio']}x", file=sys.stderr)


def _make_score_workload(n: int, d: int, k: int):
    """Synthetic GAME scoring workload: sparse fixed-effect shard +
    one dense non-projected random effect — the coordinate mix the
    fused chunk program must cover — with a model of matching shape."""
    import jax.numpy as jnp

    from photon_ml_tpu.data.sparse_rows import SparseRows
    from photon_ml_tpu.game.dataset import GameDataset, group_by_entity
    from photon_ml_tpu.models.coefficients import Coefficients
    from photon_ml_tpu.models.game import (
        FixedEffectModel,
        GameModel,
        RandomEffectModel,
    )
    from photon_ml_tpu.models.glm import TaskType

    cols, vals, labels = _make_ell(n, d, k)
    rows = SparseRows.from_flat(
        np.arange(n + 1, dtype=np.int64) * k,
        cols.reshape(-1).astype(np.int64), vals.reshape(-1))
    rng = np.random.default_rng(5)
    E = max(32, n // 100)
    ids = rng.integers(0, E, n)
    x_re = rng.normal(0, 1, (n, SCORE_D_RE)).astype(np.float32)
    grouping = group_by_entity(ids)
    blocks = [jnp.asarray(rng.normal(0, 0.1, (ne, SCORE_D_RE))
                          .astype(np.float32))
              for ne in grouping.n_entities]
    model = GameModel(models={
        "global": FixedEffectModel(
            coefficients=Coefficients(means=jnp.asarray(
                rng.normal(0, 0.1, d).astype(np.float32))),
            feature_shard="global"),
        "per_user": RandomEffectModel(
            coefficient_blocks=blocks, grouping=grouping,
            feature_shard="re", entity_key="userId"),
    })
    dataset = GameDataset(labels=labels,
                          features={"global": rows, "re": x_re},
                          entity_ids={"userId": ids})
    return model, TaskType.LOGISTIC_REGRESSION, dataset


def score_arm_main(args) -> int:
    """One arm of the ``score`` section in its OWN process (same
    rationale as ``stream_arm_main``: per-arm ``ru_maxrss`` is the
    honest high-water mark).  ``streamed`` runs the fused one-pass
    chunk pipeline with the disk tier; ``resident`` the per-coordinate
    ``GameTransformer.transform``.  Emits one JSON line and saves the
    margins for the parent's cross-arm parity check."""
    from photon_ml_tpu.estimators.game_transformer import GameTransformer

    arm = args.score_arm
    n, d, k = args.n, args.d, args.k
    model, task, dataset = _make_score_workload(n, d, k)
    transformer = GameTransformer(model=model, task=task)
    chunk_rows = -(-n // SCORE_CHUNKS)
    base_mb = _current_rss_mb()
    base_anon_mb = _current_rss_mb("RssAnon")

    scorer = None
    if arm == "streamed":
        from photon_ml_tpu.estimators.streaming_scorer import (
            StreamingGameScorer,
        )

        # ONE scorer across passes: the plan (device tables + the spill
        # store's content key) is per-dataset state, derived once — a
        # production scoring run pays it once per run.
        scorer = StreamingGameScorer(
            model=model, task=task, chunk_rows=chunk_rows,
            spill_dir=os.path.join(args.cache_dir, "spill_score"),
            host_max_resident=SCORE_WINDOW,
            prefetch_depth=SCORE_DEPTH)

    last_result = {}

    def one_pass():
        if arm == "streamed":
            last_result.clear()
            last_result.update(scorer.score(dataset, keep_margins=True))
            return last_result["margins"]
        return transformer.transform(dataset)

    t0 = time.time()
    margins = one_pass()             # warm: compile + (streamed) spill
    etl_s = time.time() - t0
    times = []
    # Telemetry (metrics) over the timed passes: the streamed arm's
    # JSON gains the prefetcher overlap block (ISSUE 7).
    from photon_ml_tpu import telemetry

    tel = telemetry.start("metrics")
    with _RssSampler() as rss:
        for _ in range(SCORE_PASSES):
            t0 = time.time()
            margins = one_pass()
            times.append(time.time() - t0)
    tel_summary = tel.summary()
    tel.close()
    pass_s = float(np.median(times))
    np.save(os.path.join(args.cache_dir, f"score_margins_{arm}.npy"),
            np.asarray(margins))

    peak = _peak_rss_mb()
    anon = _current_rss_mb("RssAnon")
    rec = {
        "arm": arm,
        "warm_s": round(etl_s, 1),
        "pass_ms": round(pass_s * 1e3, 1),
        "pass_ms_all": [round(t * 1e3, 1) for t in times],
        "rows_per_sec": round(n / pass_s, 1),
        "peak_rss_mb": round(peak, 1),
        "sweep_peak_rss_mb": round(rss.peak_mb, 1),
        "rss_delta_mb": (round(rss.peak_mb - base_mb, 1)
                         if base_mb is not None else None),
        "anon_delta_mb": (round(anon - base_anon_mb, 1)
                          if anon is not None
                          and base_anon_mb is not None else None),
        "telemetry": _telemetry_block(tel_summary,
                                      sweeps_key="score.passes"),
    }
    if arm == "streamed":
        # The ACTUAL chunk count from the scorer (ceil rounding can
        # land below the SCORE_CHUNKS target at tiny n) — the
        # window-vs-chunks evidence must not overstate itself.
        rec.update({"n_chunks": last_result.get("n_chunks"),
                    "chunk_rows": chunk_rows,
                    "host_max_resident": SCORE_WINDOW,
                    "prefetch_depth": SCORE_DEPTH,
                    # Window-bound evidence: live decoded chunks during
                    # the last timed pass never exceeded the LRU window.
                    "peak_live_chunks": last_result.get(
                        "store", {}).get("peak_resident")})
    print(json.dumps(rec))
    return 0


def section_score(ctx: BenchContext) -> None:
    """Streaming fused scoring vs per-coordinate resident scoring
    (ISSUE 4 tentpole measurement): the SAME model × dataset scored by
    both paths, each arm in its own subprocess (honest per-arm peak
    RSS).  Claims under test: margins identical to float tolerance,
    streamed peak RSS bounded by the chunk window (chunks total
    SCORE_CHUNKS/SCORE_WINDOW = 8× the window), pass time within ~1.1×
    of resident."""
    import shutil
    import subprocess

    shutil.rmtree(os.path.join(ctx.cache_dir, "spill_score"),
                  ignore_errors=True)   # honest cold spill ETL

    def run_arm(arm: str) -> dict:
        t0 = time.time()
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--score-arm", arm, "--n", str(ctx.n), "--d", str(ctx.d),
             "--k", str(ctx.k), "--cache-dir", ctx.cache_dir]
            + (["--no-compile-cache"] if ctx.no_compile_cache else []),
            capture_output=True, text=True,
            timeout=max(60.0, ctx.remaining()),
        )
        sys.stderr.write(proc.stderr)
        if proc.returncode != 0:
            raise RuntimeError(f"score arm {arm!r} failed "
                               f"(rc={proc.returncode}): "
                               f"{proc.stderr[-500:]}")
        rec = json.loads(
            [ln for ln in proc.stdout.splitlines() if ln.strip()][-1])
        rec["arm_wall_s"] = round(time.time() - t0, 1)
        return rec

    streamed = run_arm("streamed")
    resident = run_arm("resident")
    m_s = np.load(os.path.join(ctx.cache_dir,
                               "score_margins_streamed.npy"))
    m_r = np.load(os.path.join(ctx.cache_dir,
                               "score_margins_resident.npy"))
    parity = float(np.max(np.abs(m_s - m_r))) if len(m_s) else 0.0

    def ratio(a, b):
        if a is None or b is None or b == 0:
            return None
        return round(a / b, 2)

    ctx.record["score"] = {
        "n_chunks": streamed.get("n_chunks", SCORE_CHUNKS),
        "host_max_resident": SCORE_WINDOW,
        "prefetch_depth": SCORE_DEPTH,
        "passes_timed": SCORE_PASSES,
        "streamed": streamed,
        "resident": resident,
        "margin_parity_max": parity,
        "pass_time_ratio": ratio(streamed["pass_ms"],
                                 resident["pass_ms"]),
        "peak_rss_ratio": ratio(resident["peak_rss_mb"],
                                streamed["peak_rss_mb"]),
        "rss_delta_ratio": ratio(resident["rss_delta_mb"],
                                 streamed["rss_delta_mb"]),
    }
    s = ctx.record["score"]
    print(f"score: streamed {streamed['pass_ms']} ms/pass "
          f"({streamed['rows_per_sec']} rows/s, peak RSS "
          f"{streamed['peak_rss_mb']} MB) vs resident "
          f"{resident['pass_ms']} ms/pass ({resident['rows_per_sec']} "
          f"rows/s, peak {resident['peak_rss_mb']} MB); time ratio "
          f"{s['pass_time_ratio']}x, parity {parity:.2e}",
          file=sys.stderr)


def _make_re_workload(n: int, seed: int = 9):
    """Synthetic random-effect workload with power-law-ish entity skew
    (a long tail of small entities + a head of heavy ones → several
    size buckets) and per-entity offset decay rates for the retirement
    curve.  Returns (dataset, entity decay rates, base offset noise)."""
    from photon_ml_tpu.game.dataset import GameDataset

    rng = np.random.default_rng(seed)
    e_small = max(8, n // 64)
    e_big = max(2, e_small // 16)
    n_small = (3 * n) // 4
    ids = np.concatenate([
        rng.integers(0, e_small, n_small),
        rng.integers(e_small, e_small + e_big, n - n_small),
    ]).astype(np.int64)
    E = e_small + e_big
    x = rng.normal(0, 1, (n, RE_D)).astype(np.float32)
    w_true = rng.normal(0, 0.5, (E, RE_D)).astype(np.float32)
    margins = np.einsum("np,np->n", x, w_true[ids])
    labels = (rng.uniform(size=n) < 1.0 / (1.0 + np.exp(-margins)))
    dataset = GameDataset(labels=labels.astype(np.float32),
                          features={"re": x}, entity_ids={"u": ids})
    decay = rng.uniform(0.05, 0.6, E).astype(np.float32)
    base = rng.normal(0, 0.3, n).astype(np.float32)
    return dataset, ids, decay, base


def re_arm_main(args) -> int:
    """One arm of the ``re`` section in its OWN process (per-arm
    ``ru_maxrss`` honesty, as in ``stream_arm_main``): RE_SWEEPS
    emulated CD sweeps — per-entity offsets decay at entity-specific
    rates toward a fixed point, the converging-endgame shape — over
    the streamed (chunk store + prefetch + retirement) or resident
    random-effect coordinate.  Emits one JSON line; saves the final
    coefficients and scores for the parent's cross-arm parity check."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.data.normalization import NormalizationContext
    from photon_ml_tpu.game.coordinates import (
        build_random_effect_coordinate,
        build_streamed_random_effect_coordinate,
    )
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.ops.objective import GLMObjective
    from photon_ml_tpu.ops.regularization import RegularizationContext
    from photon_ml_tpu.optim import OptimizerConfig

    arm = args.re_arm
    n = args.n
    dataset, ids, decay, base = _make_re_workload(n)
    E = len(decay)
    obj = GLMObjective(
        loss=losses.LOGISTIC,
        reg=RegularizationContext.l2(1.0),
        norm=NormalizationContext.identity(),
    )
    cfg = OptimizerConfig(max_iters=60, tolerance=RE_TOL)
    base_mb = _current_rss_mb()
    base_anon_mb = _current_rss_mb("RssAnon")

    t0 = time.time()
    if arm == "streamed":
        chunk_entities = max(1, -(-E // RE_CHUNKS))
        coord = build_streamed_random_effect_coordinate(
            "u", dataset, "re", obj, config=cfg,
            spill_dir=os.path.join(args.cache_dir, "spill_re"),
            chunk_entities=chunk_entities,
            host_max_resident=RE_WINDOW, prefetch_depth=RE_DEPTH,
            retirement=True)
    else:
        coord = build_random_effect_coordinate(
            "u", dataset, "re", obj, config=cfg)
    etl_s = time.time() - t0

    per_ex_decay = decay[ids]
    times, solved, retired = [], [], []
    w = None
    scores = None

    def sweep(s):
        nonlocal w, scores
        # Squared exponent: per-entity offset deltas cross the
        # retirement tolerance on DIFFERENT sweeps (fast-decay
        # entities around sweep 3, slow ones near the end) — the
        # gradual work-reduction curve of a real CD endgame.
        off = jnp.asarray(base * (per_ex_decay ** (2 * s)))
        t0 = time.time()
        w, diag = coord.train(off, w)
        scores = coord.score(w)
        jax.block_until_ready(scores)
        times.append(time.time() - t0)
        if isinstance(diag, dict):               # streamed coordinate
            solved.append(int(diag["entities_solved"]))
            retired.append(int(diag["entities_retired"]))
            coord.retire_converged()             # the CD hook
        else:
            solved.append(E)
            retired.append(0)

    # Sweep 0 runs OUTSIDE the RSS sampler: it pays the one-time
    # per-bucket XLA compiles, whose allocator spike would set BOTH
    # arms' high-water and mask the training-regime residency
    # difference this section exists to measure (the round-8 stream
    # section's rule).  It also runs outside the telemetry window, so
    # the overlap numbers describe the steady state, not the compile
    # sweep.
    sweep(0)
    from photon_ml_tpu import telemetry

    tel = telemetry.start("metrics")
    with _RssSampler() as rss:
        for s in range(1, RE_SWEEPS):
            sweep(s)
    tel_summary = tel.summary()
    tel.close()
    # Sweep 0 pays the per-bucket XLA compiles; the steady-state number
    # is the median of the remaining sweeps.
    sweep_s = float(np.median(times[1:])) if len(times) > 1 else times[0]
    np.save(os.path.join(args.cache_dir, f"re_coefs_{arm}.npy"),
            np.concatenate([np.asarray(b).ravel() for b in w]))
    np.save(os.path.join(args.cache_dir, f"re_scores_{arm}.npy"),
            np.asarray(scores))

    peak = _peak_rss_mb()
    anon = _current_rss_mb("RssAnon")
    rec = {
        "arm": arm,
        "etl_s": round(etl_s, 1),
        "entities": E,
        "sweeps": RE_SWEEPS,
        "sweep_s": round(sweep_s, 3),
        "sweep_s_all": [round(t, 3) for t in times],
        "rows_per_sec": round(n / sweep_s, 1),
        "entities_per_sec": round(E / sweep_s, 1),
        "entities_solved_per_sweep": solved,
        "entities_retired_per_sweep": retired,
        "peak_rss_mb": round(peak, 1),
        "sweep_peak_rss_mb": round(rss.peak_mb, 1),
        "rss_delta_mb": (round(rss.peak_mb - base_mb, 1)
                         if base_mb is not None else None),
        "anon_delta_mb": (round(anon - base_anon_mb, 1)
                          if anon is not None
                          and base_anon_mb is not None else None),
        "telemetry": _telemetry_block(tel_summary,
                                      sweeps_key="re.sweeps"),
    }
    if arm == "streamed":
        store = coord.store
        rec.update({
            "n_chunks": store.n_chunks,
            "chunk_entities": coord.chunk_entities,
            "host_max_resident": RE_WINDOW,
            "prefetch_depth": RE_DEPTH,
            "peak_live_chunks": store.peak_resident,
            "disk_loads": store.loads,
            "window_hits": store.hits,
            "spill_files_mb": round(sum(
                os.path.getsize(store.path(i))
                for i in range(store.n_chunks) if store.has(i)) / 1e6, 1),
        })
    print(json.dumps(rec))
    return 0


def section_re(ctx: BenchContext) -> None:
    """Out-of-core random-effect training (ISSUE 5 tentpole
    measurement): the SAME emulated converging CD sweeps run twice —
    streamed (disk-backed entity chunks, LRU window, prefetch,
    converged-entity retirement) and resident — each arm in its own
    subprocess for honest per-arm peak RSS.  Claims under test: final
    coefficients/scores match to float tolerance despite retirement,
    live window ≤ host_max_resident, retirement reduces per-sweep
    solved entities monotonically on the converging schedule."""
    import shutil
    import subprocess

    shutil.rmtree(os.path.join(ctx.cache_dir, "spill_re"),
                  ignore_errors=True)   # honest cold spill ETL

    def run_arm(arm: str) -> dict:
        t0 = time.time()
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--re-arm", arm, "--n", str(ctx.n), "--d", str(ctx.d),
             "--k", str(ctx.k), "--cache-dir", ctx.cache_dir]
            + (["--no-compile-cache"] if ctx.no_compile_cache else []),
            capture_output=True, text=True,
            timeout=max(60.0, ctx.remaining()),
        )
        sys.stderr.write(proc.stderr)
        if proc.returncode != 0:
            raise RuntimeError(f"re arm {arm!r} failed "
                               f"(rc={proc.returncode}): "
                               f"{proc.stderr[-500:]}")
        rec = json.loads(
            [ln for ln in proc.stdout.splitlines() if ln.strip()][-1])
        rec["arm_wall_s"] = round(time.time() - t0, 1)
        return rec

    streamed = run_arm("streamed")
    resident = run_arm("resident")
    c_s = np.load(os.path.join(ctx.cache_dir, "re_coefs_streamed.npy"))
    c_r = np.load(os.path.join(ctx.cache_dir, "re_coefs_resident.npy"))
    s_s = np.load(os.path.join(ctx.cache_dir, "re_scores_streamed.npy"))
    s_r = np.load(os.path.join(ctx.cache_dir, "re_scores_resident.npy"))
    coef_parity = float(np.max(np.abs(c_s - c_r))) if len(c_s) else 0.0
    score_parity = float(np.max(np.abs(s_s - s_r))) if len(s_s) else 0.0

    def ratio(a, b):
        if a is None or b is None or b == 0:
            return None
        return round(a / b, 2)

    solved = streamed["entities_solved_per_sweep"]
    ctx.record["re"] = {
        "n_chunks": streamed.get("n_chunks"),
        "host_max_resident": RE_WINDOW,
        "prefetch_depth": RE_DEPTH,
        "sweeps": RE_SWEEPS,
        "streamed": streamed,
        "resident": resident,
        "coef_parity_max": coef_parity,
        "score_parity_max": score_parity,
        # Retirement work reduction: solved entities on the last sweep
        # as a fraction of the first (monotone ↓ on this schedule).
        "retirement_work_fraction": (round(solved[-1] / solved[0], 4)
                                     if solved and solved[0] else None),
        "sweep_time_ratio": ratio(streamed["sweep_s"],
                                  resident["sweep_s"]),
        "peak_rss_ratio": ratio(resident["peak_rss_mb"],
                                streamed["peak_rss_mb"]),
        "rss_delta_ratio": ratio(resident["rss_delta_mb"],
                                 streamed["rss_delta_mb"]),
    }
    r = ctx.record["re"]
    print(f"re: streamed {streamed['sweep_s']}s/sweep "
          f"({streamed['rows_per_sec']} rows/s, peak RSS "
          f"{streamed['peak_rss_mb']} MB, window "
          f"{streamed['peak_live_chunks']}/{streamed.get('n_chunks')} "
          f"chunks) vs resident {resident['sweep_s']}s/sweep (peak "
          f"{resident['peak_rss_mb']} MB); solved/sweep {solved}; "
          f"coef parity {coef_parity:.2e}", file=sys.stderr)


def _make_cd_fused_workload(n: int, d: int, k: int, seed: int = 11):
    """Synthetic GAME workload for the fused-CD section: a sparse
    fixed-effect shard (the chunked master grid) + a dense random
    effect with skewed entity sizes (several buckets, like the re
    section), labels from both planes so neither coordinate is
    decorative."""
    from photon_ml_tpu.game.dataset import GameDataset

    rng = np.random.default_rng(seed)
    cols, vals, _ = _make_ell(n, d, k, seed=seed)
    e_small = max(8, n // 256)
    e_big = max(2, e_small // 16)
    n_small = (3 * n) // 4
    ids = np.concatenate([
        rng.integers(0, e_small, n_small),
        rng.integers(e_small, e_small + e_big, n - n_small),
    ]).astype(np.int64)
    E = e_small + e_big
    x_re = rng.normal(0, 1, (n, CDF_D_RE)).astype(np.float32)
    w_fe = rng.normal(0, 1, d).astype(np.float32)
    w_re = rng.normal(0, 0.5, (E, CDF_D_RE)).astype(np.float32)
    margins = (np.einsum("nk,nk->n", vals, w_fe[cols])
               + np.einsum("np,np->n", x_re, w_re[ids]))
    labels = (rng.uniform(size=n)
              < 1.0 / (1.0 + np.exp(-margins))).astype(np.float32)
    rows = [(cols[i], vals[i]) for i in range(n)]
    return GameDataset(labels=labels,
                       features={"fe": rows, "re": x_re},
                       entity_ids={"u": ids},
                       feature_dims={"fe": d})


def cd_fused_arm_main(args) -> int:
    """One arm of the ``cd_fused`` section in its OWN process (per-arm
    ``ru_maxrss`` honesty): the same chunked FE + dense-RE workload
    trained with ``cd_fused`` on (``fused``) or off (``percoord``).
    A 1-cycle warm-up fit pays the XLA compiles and spills the chunk
    stores; the MEASURED fit then runs with a warm everything — its
    ``compiles`` count is the zero-new-compiles-after-warmup claim.
    Emits one JSON line; saves final coefficients for the parent's
    cross-arm parity check."""
    from photon_ml_tpu import telemetry
    from photon_ml_tpu.config import (
        CoordinateConfig,
        CoordinateKind,
        OptimizerSettings,
        TrainingConfig,
    )
    from photon_ml_tpu.estimators.game_estimator import GameEstimator
    from photon_ml_tpu.models.glm import TaskType

    arm = args.cd_fused_arm
    n = args.n
    fused = arm == "fused"
    ds = _make_cd_fused_workload(n, args.d, args.k)
    chunk_rows = -(-n // CDF_CHUNKS)

    def cfg(iters):
        return TrainingConfig(
            task_type=TaskType.LOGISTIC_REGRESSION,
            coordinates=[
                CoordinateConfig(
                    name="global", kind=CoordinateKind.FIXED_EFFECT,
                    feature_shard="fe",
                    optimizer=OptimizerSettings(
                        max_iters=CDF_LEGACY_MAX_ITERS, reg_weight=1.0)),
                CoordinateConfig(
                    name="per_u", kind=CoordinateKind.RANDOM_EFFECT,
                    feature_shard="re", entity_key="u",
                    optimizer=OptimizerSettings(
                        max_iters=CDF_LEGACY_MAX_ITERS, reg_weight=2.0)),
            ],
            update_sequence=["global", "per_u"], n_iterations=iters,
            validation_fraction=0.0, validate_per_iteration=False,
            intercept=False, chunk_rows=chunk_rows, chunk_layout="ELL",
            cd_fused=fused,
            spill_dir=os.path.join(args.cache_dir, f"spill_cdf_{arm}"),
            host_max_resident=CDF_WINDOW, prefetch_depth=CDF_DEPTH)

    base_mb = _current_rss_mb()
    # Warm-up: compiles + chunk/sidecar spill (content-keyed — the
    # measured fit reuses the files).  Runs OUTSIDE the telemetry
    # window and the RSS sampler, the other sections' rule.
    t0 = time.time()
    warm_cfg = cfg(1)
    warm_cfg.validate()
    GameEstimator(warm_cfg).fit(ds)
    warmup_s = time.time() - t0

    iters = CDF_FUSED_CYCLES if fused else CDF_LEGACY_ITERS
    run_cfg = cfg(iters)
    run_cfg.validate()
    tel = telemetry.start("metrics")
    t0 = time.time()
    with _RssSampler() as rss:
        fit = GameEstimator(run_cfg).fit(ds)[0]
    fit_s = time.time() - t0
    tel_summary = tel.summary()
    tel.close()

    c = tel_summary.get("counters", {})
    d_ = tel_summary.get("derived", {})
    sweeps = c.get("solver.sweeps", 0)
    cycles = c.get("cd.cycles", 0)
    pass_total_s = d_.get("pass_span_total_s") or None
    pass_s = (pass_total_s / sweeps if pass_total_s and sweeps else None)
    models = fit.model.models
    np.save(os.path.join(args.cache_dir, f"cdf_fe_{arm}.npy"),
            np.asarray(models["global"].coefficients.means))
    np.save(os.path.join(args.cache_dir, f"cdf_re_{arm}.npy"),
            np.concatenate([np.asarray(b).ravel()
                            for b in models["per_u"].coefficient_blocks]))

    peak = _peak_rss_mb()
    rec = {
        "arm": arm,
        "warmup_s": round(warmup_s, 1),
        "fit_s": round(fit_s, 2),
        "cycles": cycles,
        "data_passes": sweeps,
        "passes_per_cycle": (round(sweeps / cycles, 3) if cycles
                             else None),
        "pass_s": round(pass_s, 3) if pass_s else None,
        "rows_per_sec": (round(n * sweeps / pass_total_s, 1)
                         if pass_total_s else None),
        "chunk_rows": chunk_rows,
        "n_chunks": CDF_CHUNKS,
        "peak_rss_mb": round(peak, 1),
        "fit_peak_rss_mb": round(rss.peak_mb, 1),
        "rss_delta_mb": (round(rss.peak_mb - base_mb, 1)
                         if base_mb is not None else None),
        "telemetry": _telemetry_block(tel_summary),
    }
    print(json.dumps(rec))
    return 0


def section_cd_fused(ctx: BenchContext) -> None:
    """Fused CD super-sweep vs per-coordinate training (ISSUE 11
    tentpole measurement): the same workload in two subprocess arms.
    Claims under test: the fused arm's passes/cycle ≈ 1 (vs ~C ×
    solver-iterations per cycle legacy), its per-pass time stays within
    a small factor of the legacy pass (it computes every coordinate's
    statistics per chunk), zero compiles in the measured (warm) fit,
    and the two arms' final coefficients agree at convergence."""
    import shutil
    import subprocess

    for arm in ("fused", "percoord"):
        shutil.rmtree(os.path.join(ctx.cache_dir, f"spill_cdf_{arm}"),
                      ignore_errors=True)   # honest cold spill ETL

    def run_arm(arm: str) -> dict:
        t0 = time.time()
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--cd-fused-arm", arm, "--n", str(ctx.n), "--d", str(ctx.d),
             "--k", str(ctx.k), "--cache-dir", ctx.cache_dir]
            + (["--no-compile-cache"] if ctx.no_compile_cache else []),
            capture_output=True, text=True,
            timeout=max(60.0, ctx.remaining()),
        )
        sys.stderr.write(proc.stderr)
        if proc.returncode != 0:
            raise RuntimeError(f"cd_fused arm {arm!r} failed "
                               f"(rc={proc.returncode}): "
                               f"{proc.stderr[-500:]}")
        rec = json.loads(
            [ln for ln in proc.stdout.splitlines() if ln.strip()][-1])
        rec["arm_wall_s"] = round(time.time() - t0, 1)
        return rec

    fused = run_arm("fused")
    percoord = run_arm("percoord")
    fe_f = np.load(os.path.join(ctx.cache_dir, "cdf_fe_fused.npy"))
    fe_p = np.load(os.path.join(ctx.cache_dir, "cdf_fe_percoord.npy"))
    re_f = np.load(os.path.join(ctx.cache_dir, "cdf_re_fused.npy"))
    re_p = np.load(os.path.join(ctx.cache_dir, "cdf_re_percoord.npy"))
    coef_parity = float(max(np.max(np.abs(fe_f - fe_p)),
                            np.max(np.abs(re_f - re_p))
                            if len(re_f) else 0.0))

    def ratio(a, b):
        if a is None or b is None or b == 0:
            return None
        return round(a / b, 3)

    ctx.record["cd_fused"] = {
        "n_chunks": CDF_CHUNKS,
        "host_max_resident": CDF_WINDOW,
        "prefetch_depth": CDF_DEPTH,
        "fused": fused,
        "percoord": percoord,
        "passes_per_cycle_fused": fused["passes_per_cycle"],
        "passes_per_cycle_percoord": percoord["passes_per_cycle"],
        "pass_count_ratio": ratio(percoord["passes_per_cycle"],
                                  fused["passes_per_cycle"]),
        # The fused pass computes every coordinate's statistics, so it
        # is allowed to cost more than one legacy (FE-only) pass — the
        # win is needing ~C× fewer of them per cycle.
        "pass_time_ratio": ratio(fused["pass_s"], percoord["pass_s"]),
        "coef_parity_max": coef_parity,
    }
    s = ctx.record["cd_fused"]
    print(f"cd_fused: fused {fused['passes_per_cycle']} passes/cycle "
          f"({fused['pass_s']}s/pass, {fused['cycles']} cycles, peak "
          f"RSS {fused['peak_rss_mb']} MB, compiles "
          f"{fused['telemetry']['compiles']}) vs per-coordinate "
          f"{percoord['passes_per_cycle']} passes/cycle "
          f"({percoord['pass_s']}s/pass); pass-count ratio "
          f"{s['pass_count_ratio']}x, pass-time ratio "
          f"{s['pass_time_ratio']}x, coef parity {coef_parity:.2e}",
          file=sys.stderr)


def mesh_arm_main(args) -> int:
    """One HOST of the ``mesh_stream`` section in its own process:
    joins the fleet named by the environment (the ``jax.distributed``
    env trio → psum transport; the ``PHOTON_FLEET_*`` trio → tcp
    transport; neither → a single-host control run), trains the shared
    fused-CD workload over ITS chunk shard with a per-host spill
    subdir, and writes the per-host ``run_log.jsonl`` the parent's
    fleet-report join consumes.  Emits one JSON line; saves final
    coefficients for the parent's cross-host bitwise-identity check."""
    from photon_ml_tpu import telemetry
    from photon_ml_tpu.config import (
        CoordinateConfig,
        CoordinateKind,
        OptimizerSettings,
        TrainingConfig,
        read_env,
    )
    from photon_ml_tpu.estimators.game_estimator import GameEstimator
    from photon_ml_tpu.models.glm import TaskType
    from photon_ml_tpu.parallel import fleet
    from photon_ml_tpu.utils.run_log import RunLogger

    if read_env("JAX_COORDINATOR_ADDRESS"):
        from photon_ml_tpu.cli.game_training_driver import (
            distributed_init_from_env,
        )

        distributed_init_from_env()
    fctx = fleet.initialize_from_env()
    is_fleet = fctx is not None and fctx.is_fleet
    host = fctx.host_id if is_fleet else 0
    mesh_dir = os.path.join(args.cache_dir, "mesh_stream")
    out_dir = fleet.host_dir(mesh_dir, fctx)
    os.makedirs(out_dir, exist_ok=True)

    n = args.n
    ds = _make_cd_fused_workload(n, args.d, args.k)
    chunk_rows = -(-n // MESH_CHUNKS)
    cfg = TrainingConfig(
        task_type=TaskType.LOGISTIC_REGRESSION,
        coordinates=[
            CoordinateConfig(
                name="global", kind=CoordinateKind.FIXED_EFFECT,
                feature_shard="fe",
                optimizer=OptimizerSettings(
                    max_iters=CDF_LEGACY_MAX_ITERS, reg_weight=1.0)),
            CoordinateConfig(
                name="per_u", kind=CoordinateKind.RANDOM_EFFECT,
                feature_shard="re", entity_key="u",
                optimizer=OptimizerSettings(
                    max_iters=CDF_LEGACY_MAX_ITERS, reg_weight=2.0)),
        ],
        update_sequence=["global", "per_u"], n_iterations=MESH_CYCLES,
        validation_fraction=0.0, validate_per_iteration=False,
        intercept=False, chunk_rows=chunk_rows, chunk_layout="ELL",
        cd_fused=True,
        # Shared base on purpose: the chunk builder host-shards it
        # (``fleet.host_dir``) exactly as a production config would.
        spill_dir=os.path.join(mesh_dir, "spill"),
        host_max_resident=MESH_WINDOW, prefetch_depth=MESH_DEPTH)
    cfg.validate()

    run_info = {"telemetry": "metrics"}
    if is_fleet:
        run_info.update(fleet_host=fctx.host_id,
                        fleet_hosts=fctx.n_hosts,
                        fleet_transport=fctx.transport)
    run_log_path = os.path.join(out_dir, "run_log.jsonl")
    rl = RunLogger(run_log_path, run_info=run_info)
    tel = telemetry.start("metrics", run_logger=rl)
    t0 = time.time()
    fit = GameEstimator(cfg).fit(ds)[0]
    fit_s = time.time() - t0
    tel_summary = tel.summary()
    tel.close()
    rl.close()

    c = tel_summary.get("counters", {})
    sweeps = c.get("solver.sweeps", 0)
    cycles = c.get("cd.cycles", 0)
    pass_total_s = tel_summary.get("derived", {}).get(
        "pass_span_total_s") or None
    models = fit.model.models
    tag = f"h{host}" if is_fleet else "solo"
    np.save(os.path.join(mesh_dir, f"mesh_fe_{tag}.npy"),
            np.asarray(models["global"].coefficients.means))
    np.save(os.path.join(mesh_dir, f"mesh_re_{tag}.npy"),
            np.concatenate([np.asarray(b).ravel()
                            for b in models["per_u"].coefficient_blocks]))
    rec = {
        "host": host,
        "transport": fctx.transport if is_fleet else None,
        "fit_s": round(fit_s, 2),
        "cycles": cycles,
        "data_passes": sweeps,
        "passes_per_cycle": (round(sweeps / cycles, 3) if cycles
                             else None),
        "pass_span_total_s": pass_total_s,
        "chunks_streamed": c.get("fleet.chunks_streamed", 0),
        "reduces": c.get("fleet.psums", 0),
        "barrier_wait_s": round(c.get("fleet.barrier_wait_s", 0.0), 3),
        "chunk_rows": chunk_rows,
        "n_chunks": MESH_CHUNKS,
        "peak_rss_mb": round(_peak_rss_mb(), 1),
        "run_log": run_log_path,
        "telemetry": _telemetry_block(tel_summary),
    }
    print(json.dumps(rec))
    return 0


def section_mesh_stream(ctx: BenchContext) -> None:
    """Multi-host out-of-core training (ISSUE 16 tentpole
    measurement): MESH_HOSTS worker processes train the SAME fused-CD
    workload as one chunk-synchronized fleet — each host spills +
    streams only its shard of the MESH_CHUNKS grid and the per-chunk
    partials cross hosts once per chunk step.  Transport is probed:
    real ``jax.distributed`` psum where this box supports multi-process
    CPU collectives, the local-fleet tcp coordinator otherwise (the
    same solver/schedule code either way).  Claims under test: every
    host reports the SAME reduce count (the sentinel-padded schedule's
    no-deadlock invariant), the replicated solver odometer agrees
    host-to-host with passes/cycle ≈ 1, final coefficients are bitwise
    identical across hosts, per-host peak RSS is bounded by
    shard+window (not the full grid), and the barrier-wait fraction
    stays a small tax.  The per-host run logs are joined by the SAME
    ``telemetry fleet-report`` analyzer an operator would use."""
    import shutil
    import subprocess

    from photon_ml_tpu.parallel import fleet
    from photon_ml_tpu.telemetry import fleet_report

    mesh_dir = os.path.join(ctx.cache_dir, "mesh_stream")
    shutil.rmtree(mesh_dir, ignore_errors=True)  # honest cold spill ETL
    os.makedirs(mesh_dir, exist_ok=True)

    use_psum = fleet.probe_cpu_multiprocess_collectives()
    coord = None
    envs = []
    if use_psum:
        import socket

        with socket.socket() as s:
            s.bind(("localhost", 0))
            port = s.getsockname()[1]
        envs = [{"JAX_COORDINATOR_ADDRESS": f"localhost:{port}",
                 "JAX_NUM_PROCESSES": str(MESH_HOSTS),
                 "JAX_PROCESS_ID": str(h)} for h in range(MESH_HOSTS)]
    else:
        print("mesh_stream: multi-process CPU collectives unsupported "
              "here; using the local-fleet tcp transport",
              file=sys.stderr)
        coord = fleet.ReduceCoordinator(MESH_HOSTS)
        envs = [{"PHOTON_FLEET_NUM_HOSTS": str(MESH_HOSTS),
                 "PHOTON_FLEET_HOST_ID": str(h),
                 "PHOTON_FLEET_COORDINATOR": coord.address}
                for h in range(MESH_HOSTS)]

    def spawn(extra_env):
        env = dict(os.environ)
        env.update(extra_env)
        return subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--mesh-arm", "fleet", "--n", str(ctx.n), "--d",
             str(ctx.d), "--k", str(ctx.k),
             "--cache-dir", ctx.cache_dir]
            + (["--no-compile-cache"] if ctx.no_compile_cache else []),
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)

    # All hosts MUST run concurrently (they barrier at every chunk
    # step); the fleet wall-clock is the slowest host's, measured by
    # the parent around the whole fan-out.
    t0 = time.time()
    procs = [spawn(e) for e in envs]
    recs = []
    try:
        for h, proc in enumerate(procs):
            out, err = proc.communicate(
                timeout=max(120.0, ctx.remaining()))
            sys.stderr.write(err)
            if proc.returncode != 0:
                raise RuntimeError(f"mesh host {h} failed "
                                   f"(rc={proc.returncode}): "
                                   f"{err[-500:]}")
            recs.append(json.loads(
                [ln for ln in out.splitlines() if ln.strip()][-1]))
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        if coord is not None:
            coord.close()
    fleet_wall_s = time.time() - t0

    fe = [np.load(os.path.join(mesh_dir, f"mesh_fe_h{h}.npy"))
          for h in range(MESH_HOSTS)]
    re_ = [np.load(os.path.join(mesh_dir, f"mesh_re_h{h}.npy"))
           for h in range(MESH_HOSTS)]
    coef_cross = float(max(
        max(np.max(np.abs(fe[0] - fe[h]))
            for h in range(1, MESH_HOSTS)),
        max(np.max(np.abs(re_[0] - re_[h]))
            for h in range(1, MESH_HOSTS))))

    # The operator-facing join over the per-host logs IS the section's
    # analysis layer — the bench exercises it instead of reimplementing
    # the invariants.
    fr = fleet_report.analyze(
        fleet_report.load_host_logs([r["run_log"] for r in recs]))

    spans = [r["pass_span_total_s"] for r in recs]
    span = max([s for s in spans if s], default=None)
    sweeps = fr["fleet_sweeps"] or max(
        (r["data_passes"] for r in recs), default=0)
    ctx.record["mesh_stream"] = {
        "hosts": MESH_HOSTS,
        "transport": recs[0]["transport"],
        "n_chunks": MESH_CHUNKS,
        "chunks_per_host": -(-MESH_CHUNKS // MESH_HOSTS),
        "host_max_resident": MESH_WINDOW,
        "prefetch_depth": MESH_DEPTH,
        "cycles": MESH_CYCLES,
        "fleet_wall_s": round(fleet_wall_s, 1),
        # Fleet throughput: each chunk-synchronized sweep covers the
        # full n rows ACROSS hosts, paced by the slowest host's
        # in-pass time.
        "rows_per_sec": (round(ctx.n * sweeps / span, 1)
                         if span and sweeps else None),
        "passes_per_cycle": fr["passes_per_cycle"],
        "barrier_wait_fraction": fr["max_barrier_wait_fraction"],
        "max_host_peak_rss_mb": (fr["max_peak_rss_mb"]
                                 or max(r["peak_rss_mb"]
                                        for r in recs)),
        "reduces_per_host": fr["reduces"],
        "total_chunks_streamed": fr["total_chunks_streamed"],
        "barrier_agreement": fr["barrier_agreement"],
        "odometer_agreement": fr["odometer_agreement"],
        "coef_cross_host_max": coef_cross,
        "coef_identical_across_hosts": coef_cross == 0.0,
        "fleet_report_ok": fr["ok"],
        "per_host": recs,
    }
    s = ctx.record["mesh_stream"]
    print(f"mesh_stream: {MESH_HOSTS} hosts ({s['transport']}), "
          f"reduce counts {fr['reduce_counts']}, passes/cycle "
          f"{s['passes_per_cycle']}, max barrier-wait fraction "
          f"{s['barrier_wait_fraction']:.1%}, max host peak RSS "
          f"{s['max_host_peak_rss_mb']} MB, {s['rows_per_sec']} rows/s "
          f"fleet-wide, cross-host coef delta {coef_cross:.1e}, "
          f"fleet-report {'PASS' if fr['ok'] else 'FAIL'}",
          file=sys.stderr)


class _ServeServer:
    """One subprocess-isolated model server for the serve section:
    spawn with a config dict, poll ready, post, stop.  Two of these
    run SIMULTANEOUSLY for the tracing A/B (ISSUE 14) so alternating
    probe requests hit both arms under the identical instantaneous box
    state — sequential arms on a shared 2-core box measured ±15%
    drift, an order of magnitude above the effect."""

    def __init__(self, ctx: BenchContext, cfg: dict, arm: str):
        import subprocess

        self.arm = arm
        self.cfg_path = os.path.join(ctx.cache_dir,
                                     f"serve_config_{arm}.json")
        self._info_path = os.path.join(ctx.cache_dir,
                                       f"serve_info_{arm}.json")
        if os.path.exists(self._info_path):
            os.remove(self._info_path)
        with open(self.cfg_path, "w") as f:
            json.dump(cfg, f)
        self.t_start = time.time()
        self.url: str | None = None
        self.warm_wait_s: float | None = None
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "photon_ml_tpu.serving",
             "--config", self.cfg_path, "--info-file", self._info_path],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)))

    def _startup_fail(self, msg: str):
        # Kill BEFORE reading stderr: read() on a live child's pipe
        # blocks until an EOF that never comes (the startup-timeout
        # branch reaches here with the server still running).
        if self.proc.poll() is None:
            self.proc.kill()
        _out, err = self.proc.communicate()
        return RuntimeError(
            f"serve[{self.arm}]: {msg}: {(err or '')[-500:]}")

    def wait_ready(self, deadline: float) -> None:
        import urllib.request

        while not os.path.exists(self._info_path):
            if self.proc.poll() is not None or time.time() > deadline:
                raise self._startup_fail(
                    "server never wrote its info file")
            time.sleep(0.05)
        with open(self._info_path) as f:
            self.url = json.load(f)["url"]
        while True:          # poll /healthz: warming → ready
            if self.proc.poll() is not None or time.time() > deadline:
                raise self._startup_fail("server never became ready")
            try:
                with urllib.request.urlopen(self.url + "/healthz",
                                            timeout=2) as r:
                    if json.loads(r.read())["state"] == "ready":
                        break
            except OSError:
                pass
            time.sleep(0.1)
        self.warm_wait_s = time.time() - self.t_start

    def post(self, body: bytes) -> dict:
        import urllib.request

        req = urllib.request.Request(
            self.url + "/v1/score", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            return json.loads(r.read())

    def status(self) -> dict:
        import urllib.request

        with urllib.request.urlopen(self.url + "/status",
                                    timeout=10) as r:
            return json.loads(r.read())["serving"]

    def stop(self) -> dict | None:
        """SIGTERM, drain, return the CLI's final JSON line (or None
        if the exit was unclean — the caller raises)."""
        import signal
        import subprocess

        self.proc.send_signal(signal.SIGTERM)
        try:
            stdout, stderr = self.proc.communicate(timeout=60)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            stdout, stderr = self.proc.communicate()
        sys.stderr.write(stderr[-2000:] if stderr else "")
        if self.proc.returncode != 0:
            raise RuntimeError(f"serve[{self.arm}]: server exited rc="
                               f"{self.proc.returncode}")
        return json.loads(
            [ln for ln in stdout.splitlines() if ln.strip()][-1])


def _serve_storm(srv: _ServeServer, bodies: list) -> tuple:
    """The open-loop client storm against one server: SERVE_CLIENTS
    threads each firing on a fixed schedule (queue delay lands IN the
    measured latency) — a warm storm first, then the measured one.
    → (sorted latencies, measured wall seconds)."""
    import threading

    latencies: list[list[float]] = [[] for _ in range(SERVE_CLIENTS)]
    errors: list = []

    def client(c: int, measured: bool) -> None:
        reqs_n = (SERVE_REQS_PER_CLIENT if measured
                  else SERVE_WARM_REQS)
        t0 = time.perf_counter()
        for j in range(reqs_n):
            target = t0 + j * SERVE_INTERVAL_S
            lag = target - time.perf_counter()
            if lag > 0:
                time.sleep(lag)
            body = bodies[(c * 31 + j) % len(bodies)]
            t1 = time.perf_counter()
            try:
                srv.post(body)
            except Exception as e:  # noqa: BLE001 - recorded
                errors.append(f"{type(e).__name__}: {e}")
                continue
            if measured:
                latencies[c].append(time.perf_counter() - t1)

    for measured in (False, True):     # warm storm, then the clock
        t0 = time.time()
        threads = [threading.Thread(target=client, args=(c, measured))
                   for c in range(SERVE_CLIENTS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall_s = time.time() - t0
    lat = np.asarray(sorted(x for c in latencies for x in c))
    if errors or not len(lat):
        raise RuntimeError(f"serve: {len(errors)} client error(s): "
                           f"{errors[:3]}")
    return lat, wall_s


def _serve_paired_closed_loop(off: _ServeServer, on: _ServeServer,
                              bodies: list) -> dict:
    """The tracing-overhead A/B (ISSUE 14): one request in flight,
    ALTERNATING between the live off/on servers — each pair runs under
    the same instantaneous box state, so the median pairwise delta is
    the tracing cost, not queue depth (open-loop storms here run past
    a 2-core box's capacity) and not inter-arm drift (sequential arms
    measured ±15% on the shared build box)."""
    off_lat, on_lat, deltas = [], [], []
    for j in range(SERVE_CLOSED_REQS):
        body = bodies[j % len(bodies)]
        # Alternate which arm goes first inside the pair so per-pair
        # cache/scheduler asymmetry cancels too.
        order = (off, on) if j % 2 == 0 else (on, off)
        pair = {}
        for srv in order:
            t1 = time.perf_counter()
            srv.post(body)
            pair[srv.arm] = time.perf_counter() - t1
        off_lat.append(pair["off"])
        on_lat.append(pair["on"])
        deltas.append(pair["on"] - pair["off"])
    p50_off = float(np.percentile(off_lat, 50)) * 1e3
    p50_on = float(np.percentile(on_lat, 50)) * 1e3
    delta_ms = float(np.percentile(deltas, 50)) * 1e3
    return {
        "p50_off_ms": round(p50_off, 3),
        "p50_on_ms": round(p50_on, 3),
        # The claim of record: the MEDIAN PAIRWISE delta over the off
        # p50 — each pair shares one instant of box state, so marginal
        # p50 jitter (±4% observed on the build box) cancels and the
        # per-request tracing cost survives.
        "overhead_frac": (round(delta_ms / p50_off, 4)
                          if p50_off > 0 else None),
        "median_pair_delta_ms": round(delta_ms, 4),
        "closed_reqs": SERVE_CLOSED_REQS,
    }


def section_serve(ctx: BenchContext) -> None:
    """Online serving (ISSUE 12 tentpole measurement + ISSUE 14
    tracing A/B): TWO simultaneous subprocess-isolated model servers —
    tracing off and tracing on — with the open-loop client storm on
    the ON arm (the production-shape numbers) and an alternating
    one-in-flight closed loop across BOTH arms measuring the tracing
    overhead against its ≤2% budget under identical box state.
    Claims under test: served margins match the batch scorer on the
    identical rows, client-observed p50/p99 latency and sustained
    rows/s under concurrency, micro-batch fill, the tracing stage
    medians (queue-wait / dispatch), and the server's own peak RSS —
    all from the real socket path."""
    import shutil

    from photon_ml_tpu.estimators.streaming_scorer import (
        StreamingGameScorer,
    )
    from photon_ml_tpu.io.model_io import save_game_model
    from photon_ml_tpu.serving.engine import dataset_rows

    n, d, k = ctx.n, ctx.d, ctx.k
    model, task, dataset = _make_score_workload(n, d, k)
    model_dir = os.path.join(ctx.cache_dir, "serve_model")
    shutil.rmtree(model_dir, ignore_errors=True)
    save_game_model(model, task, model_dir)

    # Request pool: real dataset rows, every 7th entity id remapped to
    # an unseen one (the fixed-effect-fallback path stays measured).
    pool_n = min(SERVE_POOL, n)
    sub = dataset.take(slice(0, pool_n))
    ids = np.array(sub.entity_ids["userId"], copy=True)
    ids[::7] = 10 ** 9 + np.arange(len(ids[::7]))
    sub.entity_ids = dict(sub.entity_ids)
    sub.entity_ids["userId"] = ids
    reqs = dataset_rows(sub, 0, pool_n)
    bodies = [json.dumps({"rows": reqs[lo: lo + SERVE_ROWS_PER_REQ]})
              .encode()
              for lo in range(0, pool_n - SERVE_ROWS_PER_REQ + 1,
                              SERVE_ROWS_PER_REQ)]

    base_cfg = {
        "model_dir": model_dir,
        "batch_rows": SERVE_BATCH_ROWS,
        "batch_deadline_ms": 2.0,
        "ell_row_capacity": max(k, 8),
        "spill_dir": os.path.join(ctx.cache_dir, "spill_serve"),
        "hot_swap_poll_s": 0.0,
        "compilation_cache_dir": (None if ctx.no_compile_cache
                                  else ctx.cache_dir),
    }
    servers: dict = {}
    try:
        on_cfg = dict(base_cfg, trace="on",
                      trace_threshold_ms=SERVE_TRACE_THRESHOLD_MS,
                      log_path=os.path.join(ctx.cache_dir,
                                            "serve_on_log.jsonl"))
        servers["on"] = on = _ServeServer(ctx, on_cfg, "on")
        servers["off"] = off = _ServeServer(
            ctx, dict(base_cfg, trace="off"), "off")
        deadline = time.time() + max(60.0, ctx.remaining())
        for srv in (on, off):
            srv.wait_ready(deadline)

        # Paired A/B FIRST, both servers equally fresh (an arm that
        # just absorbed the storm measures slower for non-tracing
        # reasons — heap/allocator history — and poisons the delta).
        overhead = _serve_paired_closed_loop(off, on, bodies)
        final = {"off": off.stop()}
        del servers["off"]

        # The open-loop storm runs on the ON arm ALONE (tracing is the
        # new default — these are the production-shape numbers of
        # record, comparable to prior rounds; the OFF arm is gone so
        # its residency cannot perturb them).
        lat, wall_s = _serve_storm(on, bodies)
        parity_out = on.post(bodies[0])
        status = on.status()
        final["on"] = on.stop()
        del servers["on"]
    except BaseException:
        # Kill AND reap any still-live server, surfacing its stderr —
        # the root cause of a serve-section failure usually lives
        # there, and an unreaped child leaks a zombie + pipe FDs for
        # the rest of the bench run.
        for srv in servers.values():
            if srv.proc.poll() is None:
                srv.proc.kill()
            try:
                _out, err = srv.proc.communicate(timeout=10)
                sys.stderr.write((err or "")[-2000:])
            except Exception:  # photon-lint: disable=swallowed-exception (best-effort teardown forensics: the original section failure is already propagating and must not be masked by a reap error)
                pass
        raise
    rows_total = len(lat) * SERVE_ROWS_PER_REQ

    # Parity: one ON-arm response vs the batch path's margins on the
    # identical rows.
    ref = StreamingGameScorer(
        model=model, task=task, chunk_rows=pool_n).score(
        sub, keep_margins=True)
    parity = float(np.max(np.abs(
        np.asarray(parity_out["margins"], np.float32)
        - ref["margins"][:SERVE_ROWS_PER_REQ])))

    stages = status.get("stages") or {}
    overhead["sampled"] = (
        (status.get("tracing") or {}).get("sampled_tail", 0)
        + (status.get("tracing") or {}).get("sampled_floor", 0))

    def _stage_p50(name: str):
        return (stages.get(name) or {}).get("p50_ms")

    ctx.record["serve"] = {
        "clients": SERVE_CLIENTS,
        "rows_per_request": SERVE_ROWS_PER_REQ,
        "requests": int(len(lat)),
        "interval_ms": SERVE_INTERVAL_S * 1e3,
        "batch_rows": SERVE_BATCH_ROWS,
        "warm_wait_s": round(on.warm_wait_s, 2),
        "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 2),
        "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 2),
        "rows_per_sec": round(rows_total / wall_s, 1),
        "wall_s": round(wall_s, 2),
        "batch_fill": status["batcher"]["batch_fill"],
        "batches": status["batcher"]["batches"],
        "margin_parity_max": parity,
        "server_peak_rss_mb": status["peak_rss_mb"],
        "server_rc": final["on"]["rc"],
        # ISSUE 14: history-gated stage medians + the paired tracing
        # overhead A/B (alternating closed loop across both live arms).
        "queue_wait_ms": _stage_p50("queue_wait"),
        "dispatch_ms": _stage_p50("dispatch"),
        "trace_overhead": overhead,
    }
    s = ctx.record["serve"]
    print(f"serve: {SERVE_CLIENTS} clients x "
          f"{SERVE_REQS_PER_CLIENT} reqs x {SERVE_ROWS_PER_REQ} rows: "
          f"p50 {s['p50_ms']} ms, p99 {s['p99_ms']} ms, "
          f"{s['rows_per_sec']} rows/s, batch fill {s['batch_fill']}, "
          f"parity {parity:.2e}, server peak RSS "
          f"{s['server_peak_rss_mb']} MB; stage medians queue_wait "
          f"{s['queue_wait_ms']} ms / dispatch {s['dispatch_ms']} ms; "
          f"tracing overhead p50 {overhead['p50_off_ms']} → "
          f"{overhead['p50_on_ms']} ms ({overhead['overhead_frac']}, "
          f"median pair delta {overhead['median_pair_delta_ms']} ms)",
          file=sys.stderr)
    _serve_fleet_arm(ctx, on.cfg_path, bodies)


def _serve_fleet_arm(ctx: BenchContext, base_cfg_path: str,
                     bodies: list) -> None:
    """Fleet arm (ISSUE 13): supervisor + SERVE_FLEET_REPLICAS replica
    subprocesses behind the frontend; one replica SIGKILLed mid-storm.
    Reports failed-request count (the retry-once contract says 0),
    supervisor-measured restart latency, and the shed fraction."""
    import shutil
    import signal
    import subprocess
    import threading
    import urllib.error
    import urllib.request

    budget = ctx.remaining()
    if budget < 90.0:
        # No silent caps: a skipped arm is recorded as skipped, not
        # absent-and-assumed-green.
        ctx.record["serve"]["fleet"] = {
            "skipped": f"budget ({budget:.0f}s remaining < 90s)"}
        print("serve: fleet arm SKIPPED (budget)", file=sys.stderr)
        return

    with open(base_cfg_path) as f:
        cfg = json.load(f)
    frontend_log = os.path.join(ctx.cache_dir, "fleet_frontend.jsonl")
    cfg.update({
        "replicas": SERVE_FLEET_REPLICAS,
        # Tight detection/restart knobs: the measured restart latency
        # should be dominated by the replica's model load + warm-up,
        # not the probe cadence.
        "probe_every_s": 0.25,
        "probe_timeout_s": 2.0,
        "restart_backoff_s": 0.25,
        # Request tracing across the fleet (ISSUE 14): the frontend
        # writes its trace log here; replicas write theirs under the
        # fleet workdir — serve-report joins them by trace id below.
        "trace": "on",
        "trace_threshold_ms": SERVE_TRACE_THRESHOLD_MS,
        "log_path": frontend_log,
    })
    fleet_cfg_path = os.path.join(ctx.cache_dir, "serve_fleet.json")
    with open(fleet_cfg_path, "w") as f:
        json.dump(cfg, f)
    fleet_dir = os.path.join(ctx.cache_dir, "fleet")
    shutil.rmtree(fleet_dir, ignore_errors=True)
    info_path = os.path.join(ctx.cache_dir, "fleet_info.json")
    if os.path.exists(info_path):
        os.remove(info_path)
    t_start = time.time()
    proc = subprocess.Popen(
        [sys.executable, "-m", "photon_ml_tpu.serving",
         "--config", fleet_cfg_path, "--info-file", info_path,
         "--fleet-dir", fleet_dir],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=os.path.dirname(os.path.abspath(__file__)))

    def _fail(msg: str):
        if proc.poll() is None:
            proc.kill()
        _out, err = proc.communicate()
        return RuntimeError(f"serve fleet: {msg}: {(err or '')[-500:]}")

    def get_json(url_: str) -> dict:
        with urllib.request.urlopen(url_, timeout=10) as r:
            return json.loads(r.read())

    try:
        deadline = time.time() + max(60.0, min(budget, 240.0))
        while not os.path.exists(info_path):
            if proc.poll() is not None or time.time() > deadline:
                raise _fail("frontend never wrote its info file")
            time.sleep(0.05)
        with open(info_path) as f:
            url = json.load(f)["url"]
        while True:     # BOTH replicas warm before the storm
            if proc.poll() is not None or time.time() > deadline:
                raise _fail("fleet never became fully ready")
            try:
                st = get_json(url + "/status")
                if st["fleet"]["ready"] == SERVE_FLEET_REPLICAS:
                    break
            except OSError:
                pass
            time.sleep(0.2)
        warm_wait_s = time.time() - t_start

        def post(body: bytes) -> dict:
            req = urllib.request.Request(
                url + "/v1/score", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as r:
                return json.loads(r.read())

        latencies: list = []
        errors: list = []
        client_sheds = [0]
        lat_lock = threading.Lock()

        def client(c: int) -> None:
            t0 = time.perf_counter()
            for j in range(SERVE_FLEET_REQS_PER_CLIENT):
                target = t0 + j * SERVE_FLEET_INTERVAL_S
                lag = target - time.perf_counter()
                if lag > 0:
                    time.sleep(lag)
                body = bodies[(c * 37 + j) % len(bodies)]
                t1 = time.perf_counter()
                try:
                    post(body)
                except urllib.error.HTTPError as e:
                    # A 429/503 shed is the DESIGNED overload answer
                    # (Retry-After), not a failed request — it rides
                    # the shed fraction, never failed_requests.
                    with lat_lock:
                        if e.code in (429, 503):
                            client_sheds[0] += 1
                        else:
                            errors.append(f"HTTP {e.code}")
                    e.read()
                    continue
                except Exception as e:  # noqa: BLE001 - recorded
                    with lat_lock:
                        errors.append(f"{type(e).__name__}: {e}")
                    continue
                with lat_lock:
                    latencies.append(time.perf_counter() - t1)

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(SERVE_CLIENTS)]
        storm_s = SERVE_FLEET_REQS_PER_CLIENT * SERVE_FLEET_INTERVAL_S
        for t in threads:
            t.start()
        # SIGKILL one READY replica mid-storm — the fault the fleet
        # exists to survive.
        time.sleep(storm_s * SERVE_FLEET_KILL_FRACTION)
        st = get_json(url + "/status")
        victim = next((r for r in st["fleet"]["replicas"]
                       if r["state"] == "ready" and r["pid"]), None)
        if victim is None:
            raise _fail(f"no ready replica to SIGKILL "
                        f"(fleet: {st['fleet']['replicas']})")
        os.kill(victim["pid"], signal.SIGKILL)
        t_kill = time.time()
        for t in threads:
            t.join()
        # The replica must come back: restarted, re-warmed, in
        # rotation.
        restart_deadline = time.time() + 120.0
        while True:
            st = get_json(url + "/status")
            if (st["fleet"]["restarts"] >= 1
                    and st["fleet"]["ready"] == SERVE_FLEET_REPLICAS):
                break
            if time.time() > restart_deadline:
                raise _fail("killed replica never rejoined the fleet")
            time.sleep(0.2)
        recovery_wall_s = time.time() - t_kill
        fe = st["frontend"]
        shed_total = fe["shed"]
        served = fe["requests"]
        shed_fraction = (shed_total / (shed_total + served)
                         if (shed_total + served) else 0.0)
        lat = np.asarray(sorted(latencies))
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            stdout, stderr = proc.communicate(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            stdout, stderr = proc.communicate()
        sys.stderr.write(stderr[-2000:] if stderr else "")
    if proc.returncode != 0:
        raise RuntimeError(f"serve fleet: frontend exited rc="
                           f"{proc.returncode}")
    final = json.loads(
        [ln for ln in stdout.splitlines() if ln.strip()][-1])

    # Cross-process trace join (ISSUE 14 acceptance): serve-report
    # over the frontend's and every replica's trace logs — the SIGKILL
    # storm guarantees retried requests, so the retry-cost column is
    # exercised, and ≥99% of replica-side tail requests must join a
    # frontend trace by trace id.
    trace_join = None
    try:
        import glob as _glob
        import io as _io

        from photon_ml_tpu.telemetry.serve_report import (
            run_serve_report,
        )

        replica_logs = sorted(_glob.glob(
            os.path.join(fleet_dir, "replica_*.jsonl")))
        if os.path.exists(frontend_log) and replica_logs:
            buf = _io.StringIO()
            rep = run_serve_report([frontend_log] + replica_logs,
                                   out=buf)
            trace_join = {
                "ok": rep["ok"],
                "join_fraction": rep["join_fraction"],
                "tail_requests": rep["tail_requests"],
                "retried_requests": rep["retried_requests"],
                "retry_cost_ms": rep["retry_cost_ms"]["total"],
                "dominant_stage": rep["dominant_stage"],
            }
            print(f"serve fleet trace join: "
                  f"{rep['joined']}/{rep['tail_requests']} tail "
                  f"requests joined "
                  f"({rep['join_fraction']}), dominant stage "
                  f"{rep['dominant_stage']}, "
                  f"{rep['retried_requests']} retried",
                  file=sys.stderr)
    except Exception as e:  # noqa: BLE001 - recorded, never fatal
        trace_join = {"error": f"{type(e).__name__}: {e}"}
        print(f"serve fleet trace join FAILED: {e}", file=sys.stderr)

    s = ctx.record["serve"]
    # History-gated claims ride at the serve.* top level.
    s["failed_requests"] = len(errors)
    s["restart_s"] = st["fleet"]["last_restart_s"]
    s["shed_fraction"] = round(shed_fraction, 4)
    s["trace_join"] = trace_join
    s["fleet"] = {
        "replicas": SERVE_FLEET_REPLICAS,
        "requests": int(len(lat)),
        "client_sheds": client_sheds[0],
        "errors": errors[:5],
        "warm_wait_s": round(warm_wait_s, 2),
        "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 2),
        "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 2),
        "retries": fe["retries"],
        "shed": shed_total,
        "restarts": st["fleet"]["restarts"],
        "recovery_wall_s": round(recovery_wall_s, 2),
        "frontend_rc": final["rc"],
    }
    print(f"serve fleet: {SERVE_FLEET_REPLICAS} replicas, SIGKILL at "
          f"{SERVE_FLEET_KILL_FRACTION:.0%}: failed "
          f"{s['failed_requests']}, retries {fe['retries']}, restart "
          f"{s['restart_s']}s (recovery wall {recovery_wall_s:.1f}s), "
          f"shed fraction {s['shed_fraction']}, p99 "
          f"{s['fleet']['p99_ms']} ms", file=sys.stderr)


def _make_tron_problem(n: int, d: int, k: int):
    """Ill-conditioned sparse logistic problem: the ``_make_ell``
    structure with per-column power-law scales (10^0 down to
    10^-TRON_SCALE_DECADES across the column range) folded into the
    values, and labels drawn from a realizable margin whose true
    coefficients are inversely scaled — every scale decade carries
    signal, so the fit must travel a real distance in the flat
    directions, exactly where limited-memory quasi-Newton pays."""
    rng = np.random.default_rng(17)
    cols, vals, _ = _make_ell(n, d, k, seed=17)
    expo = -TRON_SCALE_DECADES / max(d - 1, 1)
    vals = vals * np.power(10.0, expo * cols).astype(np.float32)
    w_true = (rng.normal(0, 1.0, d)
              / np.power(10.0, expo * np.arange(d))).astype(np.float32)
    m = np.einsum("nk,nk->n", vals, w_true[cols])
    labels = (rng.uniform(size=n)
              < 1.0 / (1.0 + np.exp(-np.clip(m, -30, 30))))
    return cols, vals, labels.astype(np.float32)


def tron_arm_main(args) -> int:
    """One arm of the ``tron`` section in its OWN process (per-arm
    ``ru_maxrss`` honesty, as in ``stream_arm_main``): the same
    ill-conditioned chunked logistic problem solved to the same
    relative gradient tolerance by the streamed TRON
    (chunk-accumulated HVPs) or the streamed L-BFGS.  A short warm
    solve pays every XLA compile — the per-chunk value+gradient / HVP
    / Hessian-diag programs and the host loop's scalar helpers —
    outside the telemetry window and the RSS sampler (the warm solve is
    the identical solve: host loops compile lazily along the
    trajectory, so only a same-trajectory warm covers every program),
    and the measured solve's ``compiles`` is the
    zero-new-compiles-after-warm-up claim.
    Passes-to-tolerance is the ``solver.sweeps`` odometer over the
    measured solve — the number every acceptance claim rides on.
    Emits one JSON line; saves final weights for the parent's
    cross-arm parity check."""
    import jax.numpy as jnp

    from photon_ml_tpu import telemetry
    from photon_ml_tpu.data.chunked_batch import build_chunked_batch
    from photon_ml_tpu.data.normalization import NormalizationContext
    from photon_ml_tpu.data.sparse_rows import SparseRows
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.ops.objective import GLMObjective
    from photon_ml_tpu.ops.regularization import RegularizationContext
    from photon_ml_tpu.optim.base import OptimizerConfig
    from photon_ml_tpu.optim.streaming import (
        ChunkedGLMObjective,
        streaming_lbfgs_solve,
        streaming_tron_solve,
    )

    arm = args.tron_arm
    n, d, k = args.n, args.d, args.k
    cols, vals, labels = _make_tron_problem(n, d, k)
    rows_sp = SparseRows.from_flat(
        np.arange(n + 1, dtype=np.int64) * k,
        cols.reshape(-1).astype(np.int64), vals.reshape(-1))
    obj = GLMObjective(
        loss=losses.LOGISTIC,
        reg=RegularizationContext.l2(TRON_L2),
        norm=NormalizationContext.identity(),
    )
    base_mb = _current_rss_mb()
    t0 = time.time()
    cb = build_chunked_batch(
        rows_sp, d, labels, n_chunks=TRON_CHUNKS, layout="ell",
        spill_dir=os.path.join(args.cache_dir, f"spill_tron_{arm}"),
        host_max_resident=TRON_WINDOW)
    cobj = ChunkedGLMObjective(obj, cb, max_resident=0,
                               prefetch_depth=TRON_DEPTH)
    etl_s = time.time() - t0
    w0 = jnp.zeros(d, jnp.float32)
    cfg = OptimizerConfig(max_iters=TRON_MAX_ITERS, tolerance=TRON_TOL)

    def solve(c):
        if arm == "tron":
            return streaming_tron_solve(
                cobj.value_and_gradient, cobj.hvp_pass, w0, c,
                hessian_diag=cobj.hessian_diagonal)
        return streaming_lbfgs_solve(cobj.value_and_gradient, w0, c)

    # Warm-up is the IDENTICAL solve (same config, same w0): both host
    # loops compile programs lazily along the trajectory — TRON's
    # boundary-exit helper only on the first trust-region wall hit,
    # L-BFGS's two-loop scalars only once curvature history exists — so
    # a cheaper warm (loose tolerance, short cap) leaves late-iteration
    # programs to register against the measured solve's zero-compile
    # claim.  First run pays every compile; second run is measured.
    t0 = time.time()
    solve(cfg)
    warmup_s = time.time() - t0

    tel = telemetry.start("metrics")
    guard_stack = ExitStack()
    compile_log = None
    if args.guards:
        from photon_ml_tpu.analysis.guards import (
            count_compiles,
            no_implicit_transfers,
        )

        compile_log = guard_stack.enter_context(count_compiles())
        guard_stack.enter_context(no_implicit_transfers("log"))
    t0 = time.time()
    with guard_stack, _RssSampler() as rss:
        res = solve(cfg)
    solve_s = time.time() - t0
    tel_summary = tel.summary()
    tel.close()

    c = tel_summary.get("counters", {})
    d_ = tel_summary.get("derived", {})
    passes = c.get("solver.sweeps", 0)
    pass_total_s = d_.get("pass_span_total_s") or None
    np.save(os.path.join(args.cache_dir, f"tron_w_{arm}.npy"),
            np.asarray(res.w))

    rec = {
        "arm": arm,
        "etl_s": round(etl_s, 1),
        "warmup_s": round(warmup_s, 1),
        "solve_s": round(solve_s, 2),
        "iterations": int(res.iterations),
        "converged": bool(res.converged),
        "grad_norm": float(res.grad_norm),
        "final_value": round(float(res.value), 6),
        "passes_to_tol": passes,
        "hvp_passes": c.get("solver.hvp_sweeps", 0),
        "ls_trials": c.get("solver.ls_trials", 0),
        "aux_passes": c.get("solver.aux_sweeps", 0),
        "pass_s": (round(pass_total_s / passes, 3)
                   if pass_total_s and passes else None),
        # Rows streamed through the device per second of pass span —
        # the streamed-throughput number the history gate watches.
        "rows_per_sec": (round(n * passes / pass_total_s, 1)
                         if pass_total_s else None),
        "n_chunks": TRON_CHUNKS,
        "peak_rss_mb": round(_peak_rss_mb(), 1),
        "solve_peak_rss_mb": round(rss.peak_mb, 1),
        "rss_delta_mb": (round(rss.peak_mb - base_mb, 1)
                         if base_mb is not None else None),
        "telemetry": _telemetry_block(tel_summary),
    }
    if compile_log is not None:
        rec["guards"] = {
            "solve_compiles": compile_log.count,
            "solve_compile_programs": sorted(set(compile_log.programs)),
            "transfer_guard": "log",
        }
    print(json.dumps(rec))
    return 0


def section_tron(ctx: BenchContext) -> None:
    """Streaming TRON vs streaming L-BFGS (ISSUE 17 tentpole
    measurement): the same ill-conditioned out-of-core logistic problem
    solved to the same relative gradient tolerance in two subprocess
    arms.  Claims under test: total data passes to tolerance
    measurably below the L-BFGS arm's (the second-order pass
    advantage), streamed throughput in the same regime as the L-BFGS
    passes (the HVP pass is one more store-bounded sweep, not a new
    memory tier), per-arm peak RSS bounded by the chunk window, and
    cross-arm coefficient parity at convergence."""
    import shutil
    import subprocess

    for arm in ("tron", "lbfgs"):
        shutil.rmtree(os.path.join(ctx.cache_dir, f"spill_tron_{arm}"),
                      ignore_errors=True)   # honest cold spill ETL

    def run_arm(arm: str) -> dict:
        t0 = time.time()
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--tron-arm", arm, "--n", str(ctx.n), "--d", str(ctx.d),
             "--k", str(ctx.k), "--cache-dir", ctx.cache_dir]
            + (["--no-compile-cache"] if ctx.no_compile_cache else [])
            + (["--guards"] if ctx.guards else []),
            capture_output=True, text=True,
            timeout=max(60.0, ctx.remaining()),
        )
        sys.stderr.write(proc.stderr)
        if proc.returncode != 0:
            raise RuntimeError(f"tron arm {arm!r} failed "
                               f"(rc={proc.returncode}): "
                               f"{proc.stderr[-500:]}")
        rec = json.loads(
            [ln for ln in proc.stdout.splitlines() if ln.strip()][-1])
        rec["arm_wall_s"] = round(time.time() - t0, 1)
        return rec

    tron = run_arm("tron")
    lbfgs = run_arm("lbfgs")
    w_t = np.load(os.path.join(ctx.cache_dir, "tron_w_tron.npy"))
    w_l = np.load(os.path.join(ctx.cache_dir, "tron_w_lbfgs.npy"))
    parity = float(np.max(np.abs(w_t - w_l)))

    def ratio(a, b):
        if a is None or b is None or b == 0:
            return None
        return round(a / b, 3)

    ctx.record["tron"] = {
        "n_chunks": TRON_CHUNKS,
        "host_max_resident": TRON_WINDOW,
        "prefetch_depth": TRON_DEPTH,
        "scale_decades": TRON_SCALE_DECADES,
        "tolerance": TRON_TOL,
        "tron": tron,
        "lbfgs": lbfgs,
        # The three gated numbers (history METRICS): the TRON arm's
        # own trajectory — its pass advantage is gated via the ratio.
        "passes_to_tol": tron["passes_to_tol"],
        "rows_per_sec": tron["rows_per_sec"],
        "peak_rss_mb": tron["solve_peak_rss_mb"],
        # >1 means TRON reached the tolerance in fewer data passes.
        "pass_advantage": ratio(lbfgs["passes_to_tol"],
                                tron["passes_to_tol"]),
        "pass_time_ratio": ratio(tron["pass_s"], lbfgs["pass_s"]),
        "coef_parity_max": parity,
    }
    s = ctx.record["tron"]
    print(f"tron: {tron['passes_to_tol']} passes to tol "
          f"({tron['iterations']} iters, conv {tron['converged']}, "
          f"{tron['pass_s']}s/pass, peak RSS "
          f"{tron['solve_peak_rss_mb']} MB) vs lbfgs "
          f"{lbfgs['passes_to_tol']} passes ({lbfgs['iterations']} "
          f"iters, conv {lbfgs['converged']}); pass advantage "
          f"{s['pass_advantage']}x, pass-time ratio "
          f"{s['pass_time_ratio']}x, coef parity {parity:.2e}",
          file=sys.stderr)


SECTION_FNS = {
    "etl": section_etl,
    "cached": section_cached,
    "grr": section_grr,
    "colmajor": section_colmajor,
    "segment_sum": section_segment_sum,
    "powerlaw": section_powerlaw,
    "chunked": section_chunked,
    "sweep": section_sweep,
    "stream": section_stream,
    "score": section_score,
    "re": section_re,
    "cd_fused": section_cd_fused,
    "serve": section_serve,
    "mesh_stream": section_mesh_stream,
    "tron": section_tron,
}


def _finalize(ctx: BenchContext, platform: str) -> dict:
    """Compose the record from whatever ran (missing pieces → null)."""
    rec = dict(ctx.record)
    t_grr = ctx.step_times.get("grr")
    xla = [ctx.step_times[v] for v in ("colmajor", "segment_sum")
           if v in ctx.step_times]
    t_best_xla = min(xla) if xla else None
    out = {
        "metric": "fused sparse GLM value+gradient throughput "
                  f"(n={ctx.n:.0e},d={ctx.d:.0e},k={ctx.k},{platform},"
                  "GRR layout)".replace("e+0", "e"),
        "value": (round(ctx.n / t_grr, 1) if t_grr else None),
        "unit": "examples/sec",
        "vs_baseline": (round(t_best_xla / t_grr, 3)
                        if t_grr and t_best_xla else None),
        "step_ms_grr": (round(t_grr * 1e3, 3) if t_grr else None),
        "step_ms_colmajor": (
            round(ctx.step_times["colmajor"] * 1e3, 3)
            if "colmajor" in ctx.step_times else None),
        "step_ms_segment_sum": (
            round(ctx.step_times["segment_sum"] * 1e3, 3)
            if "segment_sum" in ctx.step_times else None),
        "baseline_note": "vs_baseline = best XLA layout (colmajor or "
                         "segment_sum) over the GRR compiled plan; "
                         "reference publishes no numbers",
    }
    if t_grr and ctx._pair is not None:
        grr_bytes = (_grr_stream_bytes(ctx._pair)
                     + 6 * ctx.n * 4 + 4 * ctx.d * 4)
        achieved = grr_bytes / t_grr / 1e9
        out["achieved_hbm_gbps"] = round(achieved, 1)
        out["roofline_fraction"] = (
            round(achieved / V5E_PEAK_GBPS, 4)
            if platform == "tpu" else None)
        # Emitted device-cost block for the GRR step (ISSUE 8): the
        # Mosaic kernel is opaque to XLA cost_analysis (a custom call),
        # so its bytes come from the PLAN — the analytic stream count
        # _grr_stream_bytes already audits — and the roofline estimate
        # is those bytes over the platform peak.  PERF.md's hand math,
        # now a field in every bench record.
        roofline_ms = grr_bytes / (V5E_PEAK_GBPS * 1e9) * 1e3
        out["device_cost"] = {"grr_step": {
            "bytes_accessed": int(grr_bytes),
            "bytes_source": "analytic plan stream count",
            "peak_gbps": V5E_PEAK_GBPS,
            "roofline_est_ms": round(roofline_ms, 3),
            "measured_step_ms": round(t_grr * 1e3, 3),
            "roofline_fraction": (round(roofline_ms / (t_grr * 1e3), 4)
                                  if platform == "tpu" else None),
        }}
    else:
        out["achieved_hbm_gbps"] = None
        out["roofline_fraction"] = None
    out.update(rec)
    out["sections_skipped"] = ctx.skipped
    if ctx.errors:
        out["errors"] = ctx.errors
    out["budget_s"] = ctx.budget_s
    return out


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--section", default=None,
                   help="comma-separated sections to run "
                        f"({'|'.join(ALL_SECTIONS)}); default "
                        f"{','.join(DEFAULT_SECTIONS)}")
    p.add_argument("--budget-s", type=float, default=DEFAULT_BUDGET_S)
    p.add_argument("--n", type=int, default=DEFAULT_N)
    p.add_argument("--d", type=int, default=DEFAULT_D)
    p.add_argument("--k", type=int, default=DEFAULT_K)
    p.add_argument("--cache-dir", default=None,
                   help="artifact cache dir (plans + XLA); default "
                        "$PHOTON_ML_TPU_BENCH_CACHE or a stable tempdir "
                        "path, so repeated driver runs hit warm")
    p.add_argument("--no-compile-cache", action="store_true",
                   help="do not enable the persistent XLA cache")
    p.add_argument("--history-dir", default=None,
                   help="append this run's JSON record (as a "
                        "schema-versioned envelope file) into the "
                        "directory; gate the trajectory with "
                        "python -m photon_ml_tpu.telemetry history")
    p.add_argument("--guards", action="store_true",
                   help="run guard-instrumented sections (currently "
                        "stream) under photon_ml_tpu.analysis.guards: "
                        "compile counting over the timed sweeps "
                        "(steady state must compile nothing) and "
                        "jax.transfer_guard('log') over the per-chunk "
                        "dispatch loop; results land in the section "
                        "record under 'guards'")
    p.add_argument("--monitor", action="store_true",
                   help="run the stream arms with the live monitor on "
                        "(ISSUE 10): progress snapshots + online alert "
                        "evaluation + an ephemeral /status endpoint "
                        "span the timed sweeps, and each arm's JSON "
                        "embeds its 'progress' block — the knob the "
                        "monitoring-overhead measurement flips")
    p.add_argument("--stream-arm", choices=("spilled", "resident"),
                   default=None,
                   help="internal: run ONE arm of the stream section "
                        "in this process (per-arm peak-RSS isolation)")
    p.add_argument("--score-arm", choices=("streamed", "resident"),
                   default=None,
                   help="internal: run ONE arm of the score section "
                        "in this process (per-arm peak-RSS isolation)")
    p.add_argument("--cd-fused-arm", choices=("fused", "percoord"),
                   default=None,
                   help="internal: run ONE cd_fused-section arm in this "
                        "process and emit its JSON line")
    p.add_argument("--re-arm", choices=("streamed", "resident"),
                   default=None,
                   help="internal: run ONE arm of the re section "
                        "in this process (per-arm peak-RSS isolation)")
    p.add_argument("--mesh-arm", choices=("fleet", "solo"),
                   default=None,
                   help="internal: run ONE host of the mesh_stream "
                        "section in this process (fleet identity comes "
                        "from the environment; without fleet env vars "
                        "this is a single-host control run)")
    p.add_argument("--tron-arm", choices=("tron", "lbfgs"),
                   default=None,
                   help="internal: run ONE arm of the tron section "
                        "in this process (per-arm peak-RSS isolation)")
    args = p.parse_args(argv)
    if args.cache_dir is None:
        # Per-user default: a fixed shared-/tmp path would let another
        # user on the host own (or poison) the plan and XLA caches.
        args.cache_dir = os.environ.get(
            "PHOTON_ML_TPU_BENCH_CACHE",
            os.path.join(tempfile.gettempdir(),
                         f"photon_ml_tpu_bench_{os.getuid()}"))

    sections = (tuple(s for s in args.section.split(",") if s)
                if args.section else DEFAULT_SECTIONS)
    unknown = [s for s in sections if s not in SECTION_FNS]
    if unknown:
        p.error(f"unknown sections {unknown}; pick from {ALL_SECTIONS}")

    if not args.no_compile_cache:
        from photon_ml_tpu.cache import enable_compilation_cache

        enable_compilation_cache(args.cache_dir)

    if args.stream_arm:
        return stream_arm_main(args)
    if args.score_arm:
        return score_arm_main(args)
    if args.re_arm:
        return re_arm_main(args)
    if args.cd_fused_arm:
        return cd_fused_arm_main(args)
    if args.mesh_arm:
        return mesh_arm_main(args)
    if args.tron_arm:
        return tron_arm_main(args)

    import jax

    platform = jax.devices()[0].platform
    ctx = BenchContext(args)
    print(f"platform={platform} n={ctx.n} d={ctx.d} k={ctx.k} "
          f"budget={args.budget_s:.0f}s sections={','.join(sections)}",
          file=sys.stderr)

    for s in sections:
        est = ctx.estimate(s)
        if ctx.remaining() < est:
            ctx.skipped.append(s)
            print(f"SKIP {s}: {ctx.remaining():.0f}s left < ~{est:.0f}s "
                  "estimated", file=sys.stderr)
            continue
        try:
            SECTION_FNS[s](ctx)
        except Exception as e:  # record, keep the run parseable
            traceback.print_exc()
            ctx.errors[s] = f"{type(e).__name__}: {e}"
        finally:
            # Memory trajectory alongside wall-clock: the process
            # high-water RSS after each section (monotone — a jump
            # names the section that caused it).
            ctx.record.setdefault("peak_rss_mb", {})[s] = round(
                _peak_rss_mb(), 1)

    out = _finalize(ctx, platform)
    if args.section and len(sections) == 1:
        # Single-section invocation: emit just that section's slice
        # (still one JSON object on the last line).
        out["section"] = sections[0]
    if args.history_dir:
        # One envelope file per run (ISSUE 8 trajectory gating): the
        # record the last stdout line carries, plus the schema/argv
        # header `telemetry history` consumes.  Filename sorts by
        # wall-clock so directory order is round order.
        os.makedirs(args.history_dir, exist_ok=True)
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
        path = os.path.join(args.history_dir,
                            f"bench_{stamp}_{os.getpid()}.json")
        with open(path, "w") as f:
            json.dump({"schema": 1, "kind": "bench_record",
                       "ts": time.time(), "argv": sys.argv[1:],
                       "rc": 0, "record": out}, f)
        print(f"history record appended: {path}", file=sys.stderr)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
