"""Benchmark: fused GLM value+gradient pass at realistic sparse scale.

Measures the framework's hot loop — the fused margin→loss→d1→scatter
gradient pipeline (the reference's ``ValueAndGradientAggregator`` +
``treeAggregate``, SURVEY.md §2.2) — on whatever accelerator jax
provides (the driver runs this on one real TPU chip).

Workload: n=1,000,000 examples, d=100,000 features, k=30 nnz/row padded
ELL (KDD-2012-class sparsity).  Metric: examples/sec through one full
value+gradient evaluation (the unit of work per optimizer iteration).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
The reference publishes no benchmark numbers (BASELINE.md), so
``vs_baseline`` is the ratio against the framework's own non-fused
two-pass XLA formulation (value pass + separate gradient pass) — the
naive implementation a straight port would produce; >1 means the fused
design wins.
"""

from __future__ import annotations

import json
import sys

import numpy as np


def _make_ell(n: int, d: int, k: int, seed: int = 0):
    """Vectorized synthetic ELL batch: unique col ids per row by
    stratified sampling (one column per d/k-wide block)."""
    rng = np.random.default_rng(seed)
    block = d // k
    cols = (np.arange(k, dtype=np.int64) * block)[None, :] + rng.integers(
        0, block, (n, k)
    )
    vals = rng.normal(0, 1, (n, k)).astype(np.float32)
    labels = (rng.uniform(size=n) < 0.5).astype(np.float32)
    return cols.astype(np.int32), vals, labels


def _time_fn(fn, *args, iters: int = 20) -> float:
    """Seconds per call via queue-drain timing (``utils.timing.measure``):
    ``jax.block_until_ready`` is unreliable through async dispatch tunnels
    (returns before device execution), so fence with a host fetch after
    dispatching ``iters`` calls back to back."""
    from photon_ml_tpu.utils.timing import measure

    return measure(fn, *args, iters=iters)


def main() -> None:
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.data.batch import SparseBatch
    from photon_ml_tpu.data.normalization import NormalizationContext
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.ops.objective import GLMObjective
    from photon_ml_tpu.ops.regularization import RegularizationContext

    n, d, k = 1_000_000, 100_000, 30
    platform = jax.devices()[0].platform
    print(f"platform={platform} n={n} d={d} k={k}", file=sys.stderr)

    cols, vals, labels = _make_ell(n, d, k)
    batch = SparseBatch(
        values=jnp.asarray(vals),
        col_ids=jnp.asarray(cols),
        labels=jnp.asarray(labels),
        weights=jnp.ones((n,), jnp.float32),
        offsets=jnp.zeros((n,), jnp.float32),
        mask=jnp.ones((n,), jnp.float32),
        dim=d,
    )
    obj = GLMObjective(
        loss=losses.LOGISTIC,
        reg=RegularizationContext.l2(1.0),
        norm=NormalizationContext.identity(),
    )
    w = jnp.asarray(np.random.default_rng(1).normal(0, 0.1, d), jnp.float32)

    # Fused single-pass value+gradient (the framework's design).
    fused = jax.jit(obj.value_and_gradient)

    # Naive two-pass baseline: separate value pass and autodiff gradient
    # pass (what a non-fused port of the reference's aggregator would do).
    value_only = jax.jit(obj.value)
    grad_only = jax.jit(jax.grad(obj.value))

    def two_pass(w, batch):
        return value_only(w, batch), grad_only(w, batch)

    t_fused = _time_fn(fused, w, batch)
    t_naive = _time_fn(two_pass, w, batch)

    examples_per_sec = n / t_fused
    # HBM traffic estimate for the fused pass: read values+col_ids twice
    # (margin pass + grad pass) + per-row vectors + [d] gradient writes.
    bytes_moved = 2 * (n * k * 8) + 5 * n * 4 + 3 * d * 4
    gb_per_sec = bytes_moved / t_fused / 1e9

    print(
        f"fused={t_fused * 1e3:.2f}ms naive={t_naive * 1e3:.2f}ms "
        f"examples/s={examples_per_sec:.3e} est-BW={gb_per_sec:.1f}GB/s",
        file=sys.stderr,
    )

    print(json.dumps({
        "metric": "fused sparse GLM value+gradient throughput "
                  f"(n=1e6,d=1e5,k=30,{platform})",
        "value": round(examples_per_sec, 1),
        "unit": "examples/sec",
        "vs_baseline": round(t_naive / t_fused, 3),
        "step_ms": round(t_fused * 1e3, 3),
        "naive_two_pass_ms": round(t_naive * 1e3, 3),
        "est_hbm_gb_per_sec": round(gb_per_sec, 1),
    }))


if __name__ == "__main__":
    main()
